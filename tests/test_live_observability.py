"""Live observability plane (the observability PR's tentpole #2):

- Prometheus text-exposition rendering — label escaping, cumulative
  log2-µs histogram buckets, empty recorders, extra gauges;
- the per-rank HTTP metrics endpoint (``/metrics``, ``/healthz``,
  ``/summary``) and its scrape helpers;
- the tracker's ``endpoint`` wire command, the live poller, the
  fleet-merged ``/metrics``, and the ``/straggler`` snapshot —
  exercised in-process over the real wire protocol, no native lib;
- cross-rank round stitching: arrival skew, critical path, straggler
  attribution, and the counter-only live laggard heuristic;
- the crash flight recorder: bundle round-trip, keep-pruning,
  excepthook chaining, and the watchdog grace-abort seam dumping a
  bundle before exit;
- the T002 escalation-counter lint contract;
- ``tools/capture_status.py --live`` and ``tools/trace_report.py``
  rendering of flight bundles + multi-artifact skew reports;
- (slow) a real 2-worker native cluster under a chaos partition with
  the full plane on: live endpoints polled by the tracker, and a
  hung-bootstrap watchdog abort leaving a renderable flight bundle.
"""

import importlib.util
import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from rabit_tpu import telemetry
from rabit_tpu.telemetry import crossrank, flight, live, prom
from rabit_tpu.telemetry.export import build_summary
from rabit_tpu.telemetry.recorder import Recorder
from rabit_tpu.telemetry.schema import matches
from rabit_tpu.tracker.tracker import MAGIC, Tracker
from rabit_tpu.utils.config import Config
from rabit_tpu.utils.watchdog import WATCHDOG_EXIT_CODE, Watchdog

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKERS = os.path.join(ROOT, "tests", "workers")
LIB = os.path.join(ROOT, "native", "build", "librabit_tpu_core.so")


@pytest.fixture
def telem():
    telemetry.reset(capacity=256, enabled=True)
    yield
    telemetry.reset(enabled=False)


def _get(host, port, path, timeout=5.0):
    with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                timeout=timeout) as r:
        return r.headers.get("Content-Type", ""), r.read().decode()


# ------------------------------------------------- Prometheus rendering


def test_prom_label_escaping():
    assert prom.escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    doc = {"recorded": 1, "dropped": 0,
           "counters": [{"name": 'evil"name\\', "op": "", "method": "",
                         "wire": "", "bucket": "0B", "count": 1,
                         "bytes": 0, "total_s": 0.0, "max_s": 0.0,
                         "hist_log2_us": {}}]}
    text = prom.render_prometheus([({}, doc)])
    assert 'name="evil\\"name\\\\"' in text
    # every non-comment line is "name{labels} value"
    for ln in text.splitlines():
        if ln and not ln.startswith("#"):
            assert " " in ln, ln


def test_prom_histogram_cumulative_buckets():
    r = Recorder(capacity=16, enabled=True)
    # log2-µs buckets: 1.5µs -> k=1 (le 2µs), 3µs & 3.5µs -> k=2 (le 4µs)
    for dur in (1.5e-6, 3e-6, 3.5e-6):
        r.record_span("allreduce", dur, nbytes=64, op="sum")
    text = prom.render_prometheus([({}, build_summary(r.snapshot()))])
    assert "# TYPE rabit_collective_duration_seconds histogram" in text

    def bucket(le):
        for ln in text.splitlines():
            if ln.startswith("rabit_collective_duration_seconds_bucket") \
                    and f'le="{le}"' in ln:
                return float(ln.rsplit(None, 1)[1])
        raise AssertionError(f"no bucket le={le}: {text}")
    assert bucket(repr(2e-06)) == 1
    assert bucket(repr(4e-06)) == 3
    assert bucket("+Inf") == 3
    assert "rabit_collective_duration_seconds_count" in text
    assert "rabit_collective_total" in text
    assert 'op="sum"' in text


def test_prom_empty_recorder_and_gauges():
    r = Recorder(capacity=4, enabled=True)
    text = prom.render_prometheus(
        [({"rank": "7"}, build_summary(r.snapshot()))],
        gauges=[("rabit_custom_gauge", "help.", "gauge",
                 [({"k": "v"}, 2.5)])])
    assert 'rabit_telemetry_recorded_total{rank="7"} 0' in text
    assert "rabit_collective_total{" not in text  # no counters yet
    assert 'rabit_custom_gauge{k="v"} 2.5' in text
    assert text.endswith("\n")


def test_prom_multi_source_rank_labels():
    rows = []
    for rank in (0, 1):
        r = Recorder(capacity=8, enabled=True)
        r.count("engine.allreduce", nbytes=1024, op="sum")
        rows.append(({"rank": str(rank)},
                     build_summary(r.snapshot(), rank=rank)))
    text = prom.render_prometheus(rows)
    assert 'rank="0"' in text and 'rank="1"' in text
    # HELP/TYPE emitted once per family, not per source
    assert text.count("# TYPE rabit_collective_total counter") == 1


# ------------------------------------------------- rank metrics endpoint


def test_rank_server_serves_metrics_health_summary(telem):
    telemetry.record_span("engine.allreduce", 1e-3, nbytes=1 << 20,
                          op="sum", method="ring",
                          round=telemetry.collective_round(
                              "engine.allreduce"))
    srv = live.start_rank_server(0, rank=3, world=8)
    try:
        ctype, text = _get(srv.host, srv.port, "/metrics")
        assert "version=0.0.4" in ctype
        assert 'name="engine.allreduce"' in text
        assert 'rank="3"' in text
        _, health = _get(srv.host, srv.port, "/healthz")
        h = json.loads(health)
        assert h["ok"] and h["rank"] == 3 and h["world"] == 8
        assert h["pid"] == os.getpid()
        _, summary = _get(srv.host, srv.port, "/summary")
        doc = json.loads(summary)
        assert matches(doc, "telemetry_summary") and doc["rank"] == 3
        assert doc["t_base_unix"] > 0
        with pytest.raises(urllib.error.HTTPError):
            _get(srv.host, srv.port, "/nope")
        # scrape helper sees the same doc; bad port returns None
        assert live.scrape_json(srv.host, srv.port)["rank"] == 3
    finally:
        srv.stop()
    assert live.scrape_json(srv.host, srv.port, timeout=0.5) is None


def test_poll_interval_knob(monkeypatch):
    monkeypatch.delenv("RABIT_METRICS_POLL_MS", raising=False)
    assert live.poll_interval_s() == pytest.approx(2.0)
    monkeypatch.setenv("RABIT_METRICS_POLL_MS", "250")
    assert live.poll_interval_s() == pytest.approx(0.25)
    monkeypatch.setenv("RABIT_METRICS_POLL_MS", "1")  # floored
    assert live.poll_interval_s() == pytest.approx(0.05)
    cfg = Config.from_args(["rabit_metrics_poll_ms=100"])
    assert live.poll_interval_s(cfg) == pytest.approx(0.1)


# ------------------------------- tracker: endpoint cmd + poller + fleet


def _send_endpoint(tr, task_id, payload):
    with socket.create_connection((tr.host, tr.port), timeout=5) as c:
        c.sendall(struct.pack("<I", MAGIC))
        for s in ("endpoint", task_id):
            b = s.encode()
            c.sendall(struct.pack("<I", len(b)) + b)
        c.sendall(struct.pack("<I", 0))
        b = payload.encode()
        c.sendall(struct.pack("<I", len(b)) + b)
        return struct.unpack("<I", c.recv(4))[0]


def _fake_rank_server(rank, n_collectives):
    rec = Recorder(capacity=32, enabled=True)
    for i in range(n_collectives):
        rec.record_span("engine.allreduce", 1e-3 * (rank + 1),
                        nbytes=1 << 20, op="sum",
                        round=rec.next_round("engine.allreduce"))
    return live.MetricsServer(
        sources_fn=lambda: [],
        summary_fn=lambda: build_summary(rec.snapshot(), rank=rank,
                                         world_size=2)).start()


def test_tracker_live_plane_polls_and_names_straggler(monkeypatch):
    monkeypatch.setenv("RABIT_METRICS_POLL_MS", "60")
    srv0 = _fake_rank_server(0, 5)
    srv1 = _fake_rank_server(1, 2)  # lags: 3 collectives behind
    tr = Tracker(2, metrics_port=0).start()
    try:
        assert tr.live_stats()["metrics_addr"] is not None
        assert _send_endpoint(tr, "0", json.dumps(
            {"host": srv0.host, "port": srv0.port, "rank": 0})) == 1
        assert _send_endpoint(tr, "1", json.dumps(
            {"host": srv1.host, "port": srv1.port, "rank": 1})) == 1
        assert _send_endpoint(tr, "x", "not json") == 0
        deadline = time.monotonic() + 10
        while tr.live_stats()["polls"] < 2:
            assert time.monotonic() < deadline, tr.live_stats()
            time.sleep(0.05)
        host, port = tr.live_stats()["metrics_addr"]
        ctype, text = _get(host, port, "/metrics")
        assert "version=0.0.4" in ctype
        assert 'rank="0"' in text and 'rank="1"' in text
        assert "rabit_tracker_endpoints 2" in text
        assert "rabit_straggler_lag_collectives" in text
        _, sdoc = _get(host, port, "/straggler")
        strag = json.loads(sdoc)
        assert strag["signal"] is True
        assert strag["lagging_rank"] == 1
        assert strag["lag_collectives"] == 3
        assert len(strag["ranks"]) == 2
        _, health = _get(host, port, "/healthz")
        assert json.loads(health)["role"] == "tracker"
        stats = tr.live_stats()
        assert set(stats["endpoints"]) == {"0", "1"}
        assert stats["straggler"]["lagging_rank"] == 1
        # polled summaries feed the SAME end-of-run merge path
        fleet = tr.merged_metrics()
        assert fleet is not None and fleet["num_ranks"] == 2
    finally:
        tr.stop()
        srv0.stop()
        srv1.stop()


def test_tracker_c10k_gauges_in_exposition_and_capture(tmp_path,
                                                       monkeypatch):
    """ISSUE 19: the event-loop/WAL/scheduler gauges ride the tracker's
    /metrics exposition and surface as first-class fields in
    ``capture_status --live``."""
    monkeypatch.setenv("RABIT_MULTI_JOB", "1")
    tr = Tracker(2, metrics_port=0, wal_dir=str(tmp_path / "wal"),
                 multi_job=True).start()
    try:
        # a held-open connection the loop must be holding right now
        idle = socket.create_connection((tr.host, tr.port), timeout=10)
        deadline = time.monotonic() + 10
        while tr._loop.open_conns < 1:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        host, port = tr.live_stats()["metrics_addr"]
        _, text = _get(host, port, "/metrics")
        for fam in ("rabit_tracker_open_conns",
                    "rabit_tracker_loop_lag_ms",
                    "rabit_wal_snapshot_seq",
                    "rabit_sched_preemptions_total"):
            assert f"# TYPE {fam}" in text, fam
        assert "rabit_wal_snapshot_seq 0" in text  # no snapshot yet
        idle.close()

        import importlib.util as _ilu
        spec = _ilu.spec_from_file_location(
            "capture_status", os.path.join(ROOT, "tools",
                                           "capture_status.py"))
        cap = _ilu.module_from_spec(spec)
        spec.loader.exec_module(cap)
        doc, ok = cap.live_status(f"{host}:{port}")
        assert ok, doc
        assert doc["open_conns"] >= 0
        assert doc["wal_snapshot_seq"] == 0
        assert doc["sched_preemptions_total"] == 0
        assert "loop_lag_ms" in doc
    finally:
        tr.stop()


def test_tracker_without_metrics_port_stays_dark():
    tr = Tracker(1).start()
    try:
        stats = tr.live_stats()
        assert stats["metrics_addr"] is None and stats["polls"] == 0
    finally:
        tr.stop()


# ----------------------------------------------- cross-rank round math


def _snap(rank, arrivals, dur=0.01, name="engine.allreduce"):
    return {"rank": rank, "t_base_unix": 1000.0,
            "spans": [{"name": name, "t0": t, "dur": dur,
                       "attrs": {"round": i + 1}}
                      for i, t in enumerate(arrivals)]}


def test_stitch_rounds_skew_and_critical_path():
    rounds = crossrank.stitch_documents([
        _snap(0, [0.0, 1.0, 2.0]),
        _snap(1, [0.1, 1.0, 2.3], dur=0.05)])
    assert len(rounds) == 3
    r1, r2, r3 = rounds
    assert r1["straggler_rank"] == 1 and r1["first_rank"] == 0
    assert r1["skew_s"] == pytest.approx(0.1)
    assert r1["critical_path_s"] == pytest.approx(0.15)
    assert r2["skew_s"] == pytest.approx(0.0)
    assert r3["straggler_rank"] == 1
    assert r3["skew_s"] == pytest.approx(0.3)
    table = crossrank.skew_table(rounds)
    lag = [t for t in table if t["rank"] == 1][0]
    assert lag["straggler_rounds"] == 2
    assert lag["skew_caused_s"] == pytest.approx(0.4)
    assert lag["worst_skew_s"] == pytest.approx(0.3)


def test_stitch_single_rank_round_has_no_skew():
    rounds = crossrank.stitch_documents([_snap(0, [0.0])])
    assert rounds[0]["skew_s"] is None
    assert rounds[0]["straggler_rank"] is None
    assert crossrank.extract_rounds({"no": "spans"}) is None


def test_straggler_snapshot_counter_only():
    docs = {}
    for tid, n in (("a", 6), ("b", 2), ("c", 6)):
        r = Recorder(capacity=8, enabled=True)
        for _ in range(n):
            r.count("engine.allreduce", nbytes=1024)
        r.count("not.collective")  # must not count toward lag
        docs[tid] = build_summary(r.snapshot(), rank=ord(tid) - ord("a"))
    snap = crossrank.straggler_snapshot(docs)
    assert snap["signal"] is True  # a real count lag is a signal
    assert snap["lagging_rank"] == 1  # task "b"
    assert snap["candidate_rank"] == 1
    assert snap["lag_collectives"] == 4
    assert len(snap["ranks"]) == 3
    empty = crossrank.straggler_snapshot({})
    assert empty["lagging_rank"] is None and empty["signal"] is False


def _tied_count_docs(busy_a, busy_b):
    # Synchronizing collectives complete in lockstep, so counts tie; the
    # real straggler arrives last and leaves at once — least busy — while
    # the waiters burn time blocked inside the collective.
    docs = {}
    for tid, busy in (("a", busy_a), ("b", busy_b)):
        r = Recorder(capacity=8, enabled=True)
        for _ in range(4):
            r.record_span("engine.allreduce", busy / 4, nbytes=1024)
        docs[tid] = build_summary(r.snapshot(), rank=ord(tid) - ord("a"))
    return docs


def test_straggler_snapshot_tie_within_threshold_is_no_signal():
    # 0.8 s of busy skew is under BUSY_SKEW_SIGNAL_S: the tie-break
    # still names a candidate, but no rank is accused
    snap = crossrank.straggler_snapshot(_tied_count_docs(0.9, 0.1))
    assert snap["signal"] is False
    assert snap["lagging_rank"] is None
    assert snap["candidate_rank"] == 1  # least busy under ties
    assert snap["lag_collectives"] == 0
    assert abs(snap["busy_skew_s"] - 0.8) < 1e-6


def test_straggler_snapshot_tie_breaks_to_least_busy():
    # past the skew threshold the candidate IS the accused straggler
    snap = crossrank.straggler_snapshot(_tied_count_docs(1.6, 0.2))
    assert snap["signal"] is True
    assert snap["lagging_rank"] == 1
    assert snap["candidate_rank"] == 1
    assert snap["lag_collectives"] == 0
    assert abs(snap["busy_skew_s"] - 1.4) < 1e-6
    assert snap["busy_skew_s"] > crossrank.BUSY_SKEW_SIGNAL_S


def test_collective_round_ids(telem):
    assert telemetry.collective_round("x") == 1
    assert telemetry.collective_round("x") == 2
    assert telemetry.collective_round("y") == 1
    telemetry.set_enabled(False)
    assert telemetry.collective_round("x") == 0  # disabled: no advance
    telemetry.set_enabled(True)
    assert telemetry.collective_round("x") == 3


# --------------------------------------------------- flight recorder


def test_flight_round_trip_and_prune(tmp_path, telem):
    telemetry.record_span("engine.allreduce", 1e-3, nbytes=1 << 20,
                          round=telemetry.collective_round(
                              "engine.allreduce"))
    flight.note("chaos.partition", "link#0")
    fr = flight.FlightRecorder(str(tmp_path), rank=2, keep=2,
                               config_args=["rabit_telemetry=1"])
    fr.install()
    try:
        assert flight.installed() is fr
        paths = [fr.dump(f"reason{i}") for i in range(4)]
        assert all(paths)
        kept = sorted(os.listdir(tmp_path))
        assert len(kept) == 2  # keep-pruned
        with open(paths[-1]) as f:
            doc = json.load(f)
        assert matches(doc, "flight_record")
        assert doc["reason"] == "reason3" and doc["rank"] == 2
        assert doc["config"] == ["rabit_telemetry=1"]
        assert doc["telemetry"]["recorded"] == 1
        assert any(e["kind"] == "chaos.partition" for e in doc["events"])
        assert "test_flight_round_trip" in doc["stacks"]
        got = crossrank.extract_rounds(doc)
        assert got is not None and got[0] == 2
        # trigger() routes through the installed singleton
        assert flight.trigger("via_trigger") is not None
    finally:
        fr.uninstall()
    assert flight.installed() is None
    assert flight.trigger("after_uninstall") is None


def test_flight_from_config(tmp_path):
    cfg = Config.from_args([f"rabit_flight_dir={tmp_path}",
                            "rabit_flight_keep=1"])
    fr = flight.FlightRecorder.from_config(cfg, rank=0)
    try:
        assert fr is not None and fr.keep == 1
        assert flight.installed() is fr
    finally:
        fr.uninstall()
    assert flight.FlightRecorder.from_config(Config.from_args([])) is None


def test_flight_excepthook_chains(tmp_path):
    calls = []
    prev = sys.excepthook
    sys.excepthook = lambda *a: calls.append(a)
    fr = flight.FlightRecorder(str(tmp_path), rank=0).install()
    try:
        sys.excepthook(ValueError, ValueError("boom"), None)
        assert len(calls) == 1  # previous hook still ran
        bundles = [f for f in os.listdir(tmp_path)
                   if "_exception" in f]
        assert len(bundles) == 1
        with open(tmp_path / bundles[0]) as f:
            assert "boom" in json.load(f)["detail"]
    finally:
        fr.uninstall()
        sys.excepthook = prev


def test_flight_sigterm_dump(tmp_path):
    seen = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    fr = flight.FlightRecorder(str(tmp_path), rank=0).install()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5
        while not seen and time.monotonic() < deadline:
            time.sleep(0.01)
        assert seen == [signal.SIGTERM]  # previous handler chained
        assert any("_sigterm" in f for f in os.listdir(tmp_path))
    finally:
        fr.uninstall()
        signal.signal(signal.SIGTERM, prev)


def test_watchdog_abort_dumps_flight_bundle(tmp_path, telem):
    aborted = threading.Event()
    codes = []

    def seam(code):
        codes.append(code)
        aborted.set()

    fr = flight.FlightRecorder(str(tmp_path), rank=1).install()
    wd = Watchdog(floor_ms=40, abort=True, abort_fn=seam)
    try:
        with wd.guard("engine.allreduce", nbytes=1 << 20,
                      deadline_s=0.05):
            assert aborted.wait(10), "grace abort never fired"
    finally:
        wd.close()
        fr.uninstall()
    assert codes == [WATCHDOG_EXIT_CODE]
    bundles = [f for f in os.listdir(tmp_path)
               if "_watchdog_abort" in f]
    assert len(bundles) == 1
    with open(tmp_path / bundles[0]) as f:
        doc = json.load(f)
    assert doc["reason"] == "watchdog_abort"
    assert "engine.allreduce" in doc["detail"]
    # the escalation left its breadcrumbs too
    names = {c["name"] for c in doc["telemetry"]["counters"]}
    assert {"watchdog.expired", "watchdog.abort"} <= names
    assert any(e["kind"] == "watchdog_expired" for e in doc["events"])


# ------------------------------------------------------- lint T002


def _load_lint():
    spec = importlib.util.spec_from_file_location(
        "rabit_lint_t002", os.path.join(ROOT, "tools", "lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_lint_counter_contract_holds_on_repo():
    lint = _load_lint()
    for rel in lint.COUNTER_REQUIRED:
        issues = lint.check_file(os.path.join(ROOT, rel))
        assert not [i for i in issues if i[2] == "T002"], issues


def test_lint_flags_uncounted_escalation(tmp_path, monkeypatch):
    lint = _load_lint()
    bare = tmp_path / "bare.py"
    bare.write_text("def _abort(self, g):\n    self._abort_fn(86)\n")
    rel = os.path.relpath(str(bare), lint.REPO)
    monkeypatch.setitem(lint.COUNTER_REQUIRED, rel,
                        {"_abort", "_vanished"})
    codes = [c for (_, _, c, _) in lint.check_file(str(bare))]
    assert codes.count("T002") == 2  # uncounted + missing function

    good = tmp_path / "good.py"
    good.write_text("def _abort(self, g):\n"
                    "    telemetry.count('watchdog.abort')\n"
                    "    self._abort_fn(86)\n")
    rel = os.path.relpath(str(good), lint.REPO)
    monkeypatch.setitem(lint.COUNTER_REQUIRED, rel, {"_abort"})
    assert not [c for (_, _, c, _) in lint.check_file(str(good))
                if c == "T002"]


# --------------------------------------------------------- tools


def test_capture_status_live_scrape(telem):
    telemetry.count("engine.allreduce", nbytes=1024, op="sum")
    srv = live.start_rank_server(0, rank=0, world=1)
    try:
        r = subprocess.run(
            [sys.executable,
             os.path.join(ROOT, "tools", "capture_status.py"),
             "--live", f"{srv.host}:{srv.port}"],
            capture_output=True, text=True, timeout=60, cwd=ROOT)
        assert r.returncode == 0, r.stdout + r.stderr
        doc = json.loads(r.stdout)
        assert matches(doc, "live_status")
        assert doc["ok"] and doc["exposition_ok"]
        assert doc["health"]["rank"] == 0
        assert doc["collectives_total"] >= 1
    finally:
        srv.stop()
    # unreachable endpoint: nonzero exit, error in the doc
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "capture_status.py"),
         "--live", f"{srv.host}:{srv.port}"],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    assert r.returncode == 1
    assert "error" in json.loads(r.stdout)


def test_trace_report_renders_flight_and_skew(tmp_path, telem):
    for i in range(2):
        telemetry.record_span("engine.allreduce", 1e-3, nbytes=1 << 20,
                              op="sum",
                              round=telemetry.collective_round(
                                  "engine.allreduce"))
    fr = flight.FlightRecorder(str(tmp_path), rank=0)
    fpath = fr.dump("watchdog_abort", "engine.allreduce stalled")
    with open(fpath) as f:
        fdoc = json.load(f)
    # rank 1's bundle: same rounds, arrivals 0.5s later -> straggler
    pdoc = dict(fdoc, rank=1)
    pdoc["telemetry"] = dict(fdoc["telemetry"], spans=[
        dict(s, t0=s["t0"] + 0.5)
        for s in fdoc["telemetry"]["spans"]])
    peer = tmp_path / "peer_flight.json"
    peer.write_text(json.dumps(pdoc))
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "trace_report.py"),
         fpath, str(peer)],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Flight record" in r.stdout
    assert "`watchdog_abort`" in r.stdout
    assert "Cross-rank rounds" in r.stdout
    assert "Straggler: rank 1" in r.stdout


# ----------------------------------------------- slow: real cluster


@pytest.mark.slow
@pytest.mark.skipif(not os.path.isfile(LIB),
                    reason="native core not built")
def test_cluster_partition_live_plane_end_to_end(tmp_path):
    """Chaos partition with the full plane on: the tracker polls both
    ranks' endpoints mid-run, the partition expires the watchdog
    (abort off so the run completes), and the launch stats carry the
    live snapshot."""
    from rabit_tpu.tracker.launch import launch
    chaos = {"seed": 11, "rules": [
        {"kind": "partition", "window_s": [0.0, 3.0], "max_times": 1}]}
    cmd = [sys.executable, os.path.join(WORKERS, "basic_worker.py"),
           "rabit_deadline_ms=800", "rabit_watchdog_abort=0"]
    stats = {}
    env = {"RABIT_TELEMETRY": "1", "RABIT_METRICS_PORT": "0",
           "RABIT_METRICS_POLL_MS": "100",
           "RABIT_FLIGHT_DIR": str(tmp_path / "flight")}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        rc = launch(2, cmd, max_attempts=30, timeout=180, stats=stats,
                    chaos=chaos)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert rc == 0
    assert stats["chaos"]["events"] >= 1, "partition never fired"
    names = {(c["name"], c.get("provenance", ""))
             for c in stats["fleet_metrics"]["counters"]}
    assert ("watchdog.expired", "recovery") in names, names
    # chaos events were counted on the launcher-side recorder contract:
    # the injected partition shows up in the workers' watchdog counters
    # (above); the live plane saw both ranks
    lv = stats["live"]
    assert lv["metrics_addr"] is not None
    assert len(lv["endpoints"]) == 2, lv
    assert lv["polls"] >= 1, lv
    assert lv["straggler"] is not None


@pytest.mark.slow
@pytest.mark.skipif(not os.path.isfile(LIB),
                    reason="native core not built")
def test_cluster_watchdog_abort_writes_flight_bundle(tmp_path):
    """A worker stalled in C++ rendezvous (its peer never starts) hits
    the watchdog grace abort — exit 86 AND a flight bundle that
    trace_report renders with the abort reason."""
    fdir = tmp_path / "flight"
    tr = Tracker(2, ready_timeout=60.0).start()
    try:
        env = dict(os.environ, PYTHONPATH=ROOT,
                   RABIT_TELEMETRY="1",
                   RABIT_FLIGHT_DIR=str(fdir))
        env.update(tr.env(task_id="0"))
        p = subprocess.Popen(
            [sys.executable, os.path.join(WORKERS, "basic_worker.py"),
             "rabit_deadline_ms=1500"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        _, err = p.communicate(timeout=60)
        assert p.returncode == WATCHDOG_EXIT_CODE, \
            (p.returncode, err.decode(errors="replace")[-2000:])
    finally:
        tr.stop()
    bundles = [f for f in os.listdir(fdir) if "_watchdog_abort" in f]
    assert len(bundles) == 1, os.listdir(fdir)
    with open(fdir / bundles[0]) as f:
        doc = json.load(f)
    assert matches(doc, "flight_record")
    assert doc["reason"] == "watchdog_abort"
    assert "engine.init" in doc["detail"]
    assert doc["stacks"], "no thread stacks captured"
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "trace_report.py"),
         str(fdir / bundles[0])],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "`watchdog_abort`" in r.stdout
