"""Cluster-level chaos scenarios (slow tier): real multi-process
worlds under injected network faults — tracker blackout at
registration, link resets mid-collective, a partition caught by the
watchdog, a hung bootstrap escalated to exit 86, and a durable cold
restart — asserting both that the cluster completes AND that the
recovery telemetry shows what it survived (doc/fault_tolerance.md)."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(ROOT, "native", "build", "librabit_tpu_core.so")
WORKERS = os.path.join(ROOT, "tests", "workers")

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(not os.path.isfile(LIB),
                       reason="native core not built"),
]

sys.path.insert(0, ROOT)


def run_cluster(nworkers, worker, extra_args=(), env=None, chaos=None,
                timeout=180, max_attempts=30):
    """launch() wrapper returning (returncode, stats)."""
    from rabit_tpu.tracker.launch import launch
    cmd = [sys.executable, os.path.join(WORKERS, worker)] + list(extra_args)
    stats = {}
    old = {}
    if env:
        for k, v in env.items():
            old[k] = os.environ.get(k)
            os.environ[k] = v
    try:
        rc = launch(nworkers, cmd, max_attempts=max_attempts,
                    timeout=timeout, stats=stats, chaos=chaos)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return rc, stats


def _counter_names(stats):
    fleet = stats.get("fleet_metrics")
    if not fleet:
        return {}
    return {(c["name"], c.get("provenance", ""))
            for c in fleet.get("counters", [])}


def test_registration_survives_tracker_blackout():
    """Connections RST'd at the tracker front during the blackout
    window: the C++ connect retry and tracker-side respawns absorb it.
    Scoped to the tracker — a blackout on link wiring kills a peer
    mid-handshake while its neighbors block in accept, which is
    unrecoverable by design (see native/src/comm.cc LinkHandshake)."""
    chaos = {"seed": 3, "rules": [
        {"kind": "blackout", "window_s": [0.0, 2.0], "max_times": 1,
         "target": "tracker"}]}
    rc, stats = run_cluster(2, "basic_worker.py", chaos=chaos)
    assert rc == 0
    assert stats["chaos"]["events"] >= 1, "blackout never fired"


def test_live_job_bit_identical_through_submit_storm():
    """ISSUE 19: hundreds of concurrent rogue submits and half-open
    registrations hammer the tracker front for the WHOLE run while a
    real 2-rank world bootstraps and reduces. basic_worker asserts
    every collective's result against the analytic answer elementwise
    (exact for the integer ops) — the storm must not perturb a single
    bit of the live job's schedule or payloads — and admission must
    have shed or queued every rogue rather than stalling the world."""
    chaos = {"seed": 19, "rules": [
        {"kind": "job_storm", "window_s": [0.0, 120.0], "burst": 300,
         "target": "tracker"}]}
    rc, stats = run_cluster(2, "basic_worker.py", chaos=chaos,
                            env={"RABIT_MULTI_JOB": "1",
                                 "RABIT_MAX_JOBS": "1",
                                 "RABIT_ADMISSION_QUEUE": "2"})
    assert rc == 0
    assert stats["chaos"]["events"] >= 1, "storm never fired"
    assert stats["chaos"]["storm_submits"] >= 100, stats["chaos"]
    # the live world runs in the default job (no RABIT_MAX_JOBS slot),
    # so at most ONE rogue wins the single free slot; admission must
    # refuse (queue/shed/error) every other concurrent submit
    assert stats["chaos"]["storm_submits"] - \
        stats["chaos"]["storm_shed"] <= 1, stats["chaos"]


def test_collectives_survive_link_resets():
    """Each link proxy hard-resets its first connection once enough
    bytes passed — mid-collective RSTs on live recovery-capable
    workers. recover_worker's analytic checks catch any corruption the
    replay let through."""
    chaos = {"seed": 5, "rules": [
        {"kind": "reset", "after_bytes": 4096, "max_times": 1,
         "target": "link"}]}
    rc, stats = run_cluster(4, "recover_worker.py", chaos=chaos,
                            env={"N_ITER": "6"})
    assert rc == 0
    assert stats["chaos"]["events"] >= 1, "no reset ever fired"


def test_hier_collectives_survive_link_resets():
    """The two-level schedule under mid-collective link RSTs: a 4-rank
    device-plane world forced into 2 simulated hosts
    (RABIT_HIER_GROUP=2) runs every coded-op payload through
    hierarchical device allreduce while each link proxy — the
    delegates' (ranks 0 and 2) included — hard-resets its first busy
    connection once enough control-plane bytes passed. An RST mid-run
    strands the reset ranks' peers inside the abandoned gloo program
    with no socket error to react to, so the watchdog deadline is
    load-bearing here: it aborts the stuck ranks (exit 86), the
    tracker respawns them, and the device world re-forms — without the
    deadline this scenario stalls forever. N_ITER is high because with
    payloads on the device plane only control traffic crosses the
    links; the growing broadcast payloads push the trigger byte count
    past bootstrap and into mid-collective territory (an RST during
    link wiring is unrecoverable by design, see the first test)."""
    chaos = {"seed": 9, "rules": [
        {"kind": "reset", "after_bytes": 4096, "max_times": 1,
         "target": "link"}]}
    rc, stats = run_cluster(
        4, "recover_worker.py", chaos=chaos,
        extra_args=["rabit_dataplane=xla", "rabit_dataplane_minbytes=0",
                    "rabit_deadline_ms=5000"],
        env={"RABIT_DATAPLANE": "xla", "RABIT_DATAPLANE_MINBYTES": "0",
             "RABIT_REDUCE_METHOD": "hier", "RABIT_HIER_GROUP": "2",
             "RABIT_TELEMETRY": "1", "N_ITER": "40"}, timeout=240)
    assert rc == 0
    assert stats["chaos"]["events"] >= 1, "no reset ever fired"
    names = _counter_names(stats)
    assert ("recovery.world_reform", "recovery") in names, names
    # the fleet summary must show all three hierarchical phases ran
    span_names = {n for n, _ in names}
    for phase in ("hier.reduce_scatter", "hier.inter", "hier.allgather"):
        assert phase in span_names, (phase, sorted(span_names))


def test_partition_expires_watchdog_and_recovers():
    """A partition window stalls the stream without any socket error —
    invisible to the epoch machinery, visible to the watchdog. With
    abort opted out the stall is reported (recovery-provenance
    counters) and the run completes once the window passes."""
    chaos = {"seed": 11, "rules": [
        {"kind": "partition", "window_s": [0.0, 3.0], "max_times": 1}]}
    rc, stats = run_cluster(
        2, "basic_worker.py",
        extra_args=["rabit_deadline_ms=800", "rabit_watchdog_abort=0"],
        env={"RABIT_TELEMETRY": "1"}, chaos=chaos)
    assert rc == 0
    assert stats["chaos"]["events"] >= 1, "no fault fired"
    names = _counter_names(stats)
    assert ("watchdog.expired", "recovery") in names, names


def test_watchdog_aborts_hung_bootstrap_with_exit_86():
    """A worker whose world never completes rendezvous is stalled
    inside C++ socket code: only the watchdog's grace abort can free
    it, and the exit code must be distinguishable from a scripted
    kill."""
    from rabit_tpu.tracker.tracker import Tracker
    from rabit_tpu.utils.watchdog import WATCHDOG_EXIT_CODE
    tr = Tracker(2, ready_timeout=60.0).start()
    try:
        env = dict(os.environ, PYTHONPATH=ROOT)
        env.update(tr.env(task_id="0"))
        p = subprocess.Popen(
            [sys.executable, os.path.join(WORKERS, "basic_worker.py"),
             "rabit_deadline_ms=1500"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        _, err = p.communicate(timeout=60)
        assert p.returncode == WATCHDOG_EXIT_CODE, \
            (p.returncode, err.decode(errors="replace")[-2000:])
        assert b"watchdog" in err.lower()
    finally:
        tr.stop()


def test_cold_restart_resumes_from_durable_store(tmp_path):
    """Whole-world death: run to v3 and stop; a second, fully fresh
    world (native version 0 on every rank) must agree on v3 via the
    MAX/MIN/broadcast consensus and continue to v5 — even with one
    rank's disk lagging a version behind."""
    ckpt = str(tmp_path / "ckpt")
    args = [f"rabit_ckpt_dir={ckpt}", "rabit_ckpt_keep=2"]
    rc, _ = run_cluster(4, "durable_worker.py", extra_args=args,
                        env={"N_TARGET": "3", "EXPECT_VERSION": "0"})
    assert rc == 0
    for r in range(4):
        assert os.path.isfile(
            os.path.join(ckpt, f"r{r}", "ckpt_v3.rbt")), f"rank {r}"
    # rank 3's disk lags: its newest checkpoint is gone
    os.unlink(os.path.join(ckpt, "r3", "ckpt_v3.rbt"))

    rc, stats = run_cluster(
        4, "durable_worker.py", extra_args=args,
        env={"N_TARGET": "5", "EXPECT_VERSION": "3",
             "RABIT_TELEMETRY": "1"})
    assert rc == 0
    names = _counter_names(stats)
    assert ("recovery.cold_restart", "recovery") in names, names
    # every rank (including the laggard) caught up durably
    from rabit_tpu.engine.ckpt_store import CheckpointStore
    for r in range(4):
        st = CheckpointStore(ckpt, rank=r, keep=2)
        assert st.latest_version() == 5, f"rank {r}: {st.versions()}"


def test_cold_restart_empty_store_starts_at_zero(tmp_path):
    """A configured-but-empty store must behave exactly like no store:
    version 0, no consensus payload, normal run."""
    rc, _ = run_cluster(
        2, "durable_worker.py",
        extra_args=[f"rabit_ckpt_dir={tmp_path / 'none'}"],
        env={"N_TARGET": "2", "EXPECT_VERSION": "0"})
    assert rc == 0
