"""Multi-job cluster worker (tests/test_multi_job.py, ISSUE 15).

One native-engine rank of ONE job on a shared multi-job tracker. Every
round is a pure function of (round, world), so the logged CRC stream is
bit-comparable against a solo-baseline run of the same job shape — the
fault-isolation proof: a neighbor job dying mid-collective must leave
this job's stream identical to running alone.

``mj_die_at=K`` makes the rank exit hard (no shutdown, no finalize)
just before collective round K — the whole-job-crash injection for the
victim job. Config rides argv ``key=value`` pairs exactly like the
other cluster workers; ``mj_*`` keys are consumed here, the rest feed
``rabit.init``.
"""

import os
import sys
import zlib

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402

TASK = os.environ.get("RABIT_TASK_ID", "?")
COUNT = 8192


def main():
    cfg = dict(a.split("=", 1) for a in sys.argv[1:] if "=" in a)
    out_dir = cfg.pop("mj_out")
    rounds = int(cfg.pop("mj_rounds", "4"))
    die_at = int(cfg.pop("mj_die_at", "-1"))
    log_path = os.path.join(out_dir, f"r{TASK.replace('/', '_')}.log")

    def log(msg):
        with open(log_path, "a") as f:
            f.write(msg + "\n")

    rabit.init([f"{k}={v}" for k, v in cfg.items()], engine="native")
    rank, world = rabit.get_rank(), rabit.get_world_size()
    assert rabit.is_distributed()
    log(f"formed rank={rank} world={world}")

    for rnd in range(rounds):
        if rnd == die_at:
            log(f"dying round={rnd}")
            os._exit(17)    # crash: no shutdown, no finalize
        a = np.arange(COUNT, dtype=np.int64) * (rank + 1) + rnd
        out = rabit.allreduce(a, rabit.SUM)
        expect = (np.arange(COUNT, dtype=np.int64)
                  * (world * (world + 1) // 2) + rnd * world)
        np.testing.assert_array_equal(out, expect)
        log(f"sum round={rnd} world={world} "
            f"crc={zlib.crc32(out.tobytes()):08x}")

    log("done")
    rabit.finalize()


if __name__ == "__main__":
    main()
