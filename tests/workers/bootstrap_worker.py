"""Bootstrap-cache worker: performs collectives BEFORE load_checkpoint
(the pattern rabit_bootstrap_cache=1 exists for — reference
allreduce_robust.cc:89-141). A restarted worker must replay the pre-load
results from surviving holders without disturbing post-load sequence
numbering."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

if os.environ.get("RABIT_DATAPLANE") == "xla":
    # tests drive the device plane on the CPU backend (gloo); must be
    # configured before any computation touches the default backend
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402


def main() -> None:
    rabit.init()
    rank = rabit.get_rank()
    world = rabit.get_world_size()

    # --- pre-load collectives (bootstrap-cached, consume no seqnos) ----
    cfg = rabit.broadcast({"lr": 0.1, "seed": 42} if rank == 0 else None, 0)
    assert cfg["seed"] == 42
    stats = rabit.allreduce(np.full(8, float(rank + 1), np.float64),
                            rabit.SUM)
    np.testing.assert_allclose(stats, np.full(8, world * (world + 1) / 2))

    # --- load + train loop --------------------------------------------
    version, model = rabit.load_checkpoint()
    if version == 0:
        model = {"iter": 0, "lr": cfg["lr"]}
    assert model["lr"] == 0.1

    for it in range(model["iter"], 4):
        out = rabit.allreduce(np.full(16, float(rank + it), np.float32),
                              rabit.SUM)
        expect = sum(r + it for r in range(world))
        np.testing.assert_allclose(out, np.full(16, expect))
        model["iter"] = it + 1
        rabit.checkpoint(model)

    rabit.tracker_print(f"bootstrap_worker rank {rank}/{world} OK")
    rabit.finalize()


if __name__ == "__main__":
    main()
