"""Pins the prepare-skip contract on replay (reference engine.h:74-96:
prepare_fun runs lazily and is skipped when the result is replayed from
the recovery cache).

Schedule: ``mock=1,0,1,0`` kills rank 1 at its SECOND collective
(version 0, seq 1). On respawn (trial 1), rank 1 re-issues op seq 0 —
the survivors hold its result in their logs, so the robust engine
replays it and the prepare_fun must NOT run; then op seq 1 executes
fresh and prepare MUST run. Works identically with the socket and XLA
data planes.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

if os.environ.get("RABIT_DATAPLANE") == "xla":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402


def main() -> None:
    rabit.init()
    rank = rabit.get_rank()
    world = rabit.get_world_size()
    trial = int(os.environ.get("RABIT_NUM_TRIAL", "0"))
    n = 64

    version, model = rabit.load_checkpoint()

    prep_calls = []

    def prep(d):
        prep_calls.append(True)
        d[:] = np.arange(n, dtype=np.float32) + rank

    # op seq 0: replayed on rank 1's respawn => prep skipped there
    a = np.zeros(n, dtype=np.float32)
    out = rabit.allreduce(a, rabit.MAX, prepare_fun=prep)
    np.testing.assert_allclose(out, np.arange(n) + (world - 1))
    if rank == 1 and trial > 0:
        assert not prep_calls, \
            "prepare_fun ran on a REPLAYED op (must be skipped)"
    else:
        assert prep_calls, "prepare_fun did not run on a fresh op"

    # op seq 1: the respawned rank's first fresh op => prep must run
    # (the mock kills rank 1 here on trial 0)
    prep_calls.clear()
    b = np.zeros(n, dtype=np.float32)
    out = rabit.allreduce(b, rabit.MAX, prepare_fun=prep)
    np.testing.assert_allclose(out, np.arange(n) + (world - 1))
    assert prep_calls, "prepare_fun did not run on a fresh op"

    rabit.checkpoint({"done": True})
    rabit.tracker_print(f"prepare_skip_worker rank {rank}/{world} OK "
                        f"(trial {trial})")
    rabit.finalize()


if __name__ == "__main__":
    main()
