"""Timing worker for the wire-quantization byte-savings bench
(tools/wire_bench.py): K repeated float-SUM allreduces of an n-element
payload through the tracker-launched XLA data plane, wire mode from the
environment. Rank 0 prints one machine-readable line; correctness is
asserted against the analytic sum so a broken wire path cannot "win"
the timing.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402


def main() -> None:
    rabit.init()
    rank = rabit.get_rank()
    world = rabit.get_world_size()
    wire = os.environ.get("RABIT_DATAPLANE_WIRE", "none")
    n = int(os.environ.get("WIRE_BENCH_N", "65536"))
    k = int(os.environ.get("WIRE_BENCH_K", "10"))

    base = np.linspace(-1.0, 1.0, n).astype(np.float32)
    want1 = base * world  # every rank contributes the same payload
    rtol = {"bf16": 2e-2, "int8": 5e-2}.get(wire, 1e-5)

    out = rabit.allreduce(base.copy(), rabit.SUM)  # warm
    np.testing.assert_allclose(out, want1, rtol=rtol, atol=rtol * world)

    t0 = time.perf_counter()
    for it in range(k):
        out = rabit.allreduce(base.copy(), rabit.SUM)
    elapsed = time.perf_counter() - t0
    np.testing.assert_allclose(out, want1, rtol=rtol, atol=rtol * world)

    if rank == 0:
        print("WIREBENCH " + json.dumps({
            "wire": wire, "world": world, "n": n, "k": k,
            "s_per_op": elapsed / k}), flush=True)
    rabit.finalize()


if __name__ == "__main__":
    main()
