"""Skew-adaptation worker: 4 real processes over gloo with rank 2
sleeping before every collective (an injected arrival straggler).

argv: <process_id> <num_processes> <coordinator_port>

Three phases:

1. correctness — the adapted schedules (rotation via the forced
   digest; explicit pre-aggregation) must be BIT-exact against the
   flat ring on integer-valued payloads for every dtype (association-
   free, so any dropped/duplicated contribution shows up);
1b. agreement — each process forces a DIVERGENT candidate digest
   (accusing itself); the sync boundary must reconcile every rank
   onto process 0's candidate, so the whole fleet adapts around
   laggard 0. Per-process application of divergent candidates — the
   bug this phase pins — traced different static schedules per rank
   and deadlocked;
2. performance — mean fleet round time over a lagging fleet must be
   LOWER with ``rabit_skew_adapt=1`` (pre-aggregation overlaps the
   early ranks' reduction with the laggard's delay) than with the
   knob off. The lag (80 ms) dwarfs loopback noise and the payload
   (2M floats) makes the overlapped reduction worth whole
   milliseconds, so the comparison is stable on a shared CI box.
"""

import json
import os
import sys
import time
import zlib

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402
from rabit_tpu.telemetry import skew  # noqa: E402

LAG_RANK = 2
LAG_S = 0.080
ROUNDS = 6
WARMUP = 2


def _assert_ranks_identical(arr: np.ndarray, r: int) -> None:
    crc = np.array([zlib.crc32(np.ascontiguousarray(arr).tobytes())],
                   np.int64)
    hi = rabit.allreduce(crc, rabit.MAX)
    lo = rabit.allreduce(crc, rabit.MIN)
    assert hi[0] == lo[0] == crc[0], (r, int(crc[0]), int(hi[0]), int(lo[0]))


def _set_adapt(enabled: bool, w: int, preagg_ms: str) -> None:
    if enabled:
        os.environ["RABIT_SKEW_ADAPT"] = "1"
        os.environ["RABIT_SKEW_PREAGG_MS"] = preagg_ms
        os.environ["RABIT_SKEW_DIGEST"] = json.dumps(
            {"epoch": 1, "laggard": LAG_RANK,
             "offsets_ms": {str(i): (LAG_S * 1e3 if i == LAG_RANK else 0.0)
                            for i in range(w)}})
    else:
        for var in ("RABIT_SKEW_ADAPT", "RABIT_SKEW_PREAGG_MS",
                    "RABIT_SKEW_DIGEST"):
            os.environ.pop(var, None)
    skew.reset_monitor()


def _timed_rounds(xs: np.ndarray, r: int) -> float:
    """Mean FLEET round time (identical on every rank: the per-round
    max arrival-to-done time is itself allreduced)."""
    times = []
    for i in range(WARMUP + ROUNDS):
        rabit.allreduce(np.zeros(1, np.int32), rabit.SUM)  # align start
        if r == LAG_RANK:
            time.sleep(LAG_S)
        t0 = time.perf_counter()
        out = rabit.allreduce(xs, rabit.SUM)
        dt = time.perf_counter() - t0
        assert out.shape == xs.shape
        if i >= WARMUP:
            # a waiting early rank's in-call time includes the laggard's
            # sleep; the fleet round cost is the slowest rank's view
            times.append(float(rabit.allreduce(
                np.array([dt], np.float64), rabit.MAX)[0]))
    return sum(times) / len(times)


def main() -> None:
    pid, nproc, port = sys.argv[1], sys.argv[2], sys.argv[3]
    rabit.init(["rabit_engine=xla",
                f"rabit_coordinator=127.0.0.1:{port}",
                f"rabit_num_processes={nproc}",
                f"rabit_process_id={pid}"])
    r, w = rabit.get_rank(), rabit.get_world_size()
    assert w == int(nproc) == 4, (r, w)

    # ---- phase 1: adapted schedules are bit-exact vs the flat ring
    # (payload above the 32768-element crossover so auto dispatch runs
    # the RING family and the adapted plan is a rotation, not a re-root)
    base = np.arange(40009) % 89
    for dt in (np.int32, np.int64, np.float32, np.float64):
        arr = (base + r).astype(dt)
        _set_adapt(False, w, "0")
        flat = rabit.allreduce(arr, rabit.SUM)
        want = (base * w + sum(range(w))).astype(dt)
        assert np.array_equal(flat, want), (r, dt, flat[:4])
        # rotation (preagg gated off)
        _set_adapt(True, w, "0")
        rot = rabit.allreduce(arr, rabit.SUM)
        assert rot.dtype == flat.dtype and np.array_equal(rot, flat), \
            (r, dt, rot[:4])
        _assert_ranks_identical(rot, r)
        # pre-aggregation (threshold forced far below the 80ms digest)
        _set_adapt(True, w, "0.0001")
        pre = rabit.allreduce(arr, rabit.SUM)
        assert pre.dtype == flat.dtype and np.array_equal(pre, flat), \
            (r, dt, pre[:4])
        _assert_ranks_identical(pre, r)
    _set_adapt(False, w, "0")

    # ---- phase 1b: divergent candidates. Each process forces a digest
    # accusing ITSELF — maximally divergent per-process opinions. The
    # agreement boundary must reconcile the fleet onto process 0's
    # candidate before anything becomes a static jit argument; the old
    # per-process application deadlocked here (each rank traced a
    # different rotation for the same round).
    os.environ["RABIT_SKEW_ADAPT"] = "1"
    os.environ["RABIT_SKEW_PREAGG_MS"] = "0"
    os.environ["RABIT_SKEW_DIGEST"] = json.dumps(
        {"epoch": 2, "laggard": r,
         "offsets_ms": {str(i): (80.0 if i == r else 0.0)
                        for i in range(w)}})
    skew.reset_monitor()
    arr = (base + r).astype(np.int32)
    got = rabit.allreduce(arr, rabit.SUM)
    want = (base * w + sum(range(w))).astype(np.int32)
    assert np.array_equal(got, want), (r, got[:4])
    # whatever schedule family dispatch elects, the laggard it adapts
    # around must be the AGREED one (process 0's candidate), not this
    # process's own accusation
    applied = skew.last_applied()
    assert applied is not None and applied.endswith("@0"), (r, applied)
    _assert_ranks_identical(got, r)
    _set_adapt(False, w, "0")

    # ---- phase 2: lagging fleet, mean round time with/without adapt
    xs = (np.arange(2_000_000) % 251).astype(np.float32) + r
    _set_adapt(False, w, "0")
    flat_mean = _timed_rounds(xs, r)
    _set_adapt(True, w, "0.0001")
    adapt_mean = _timed_rounds(xs, r)
    _set_adapt(False, w, "0")
    print(f"rank {r}: flat {flat_mean * 1e3:.1f} ms "
          f"adapted {adapt_mean * 1e3:.1f} ms", flush=True)
    assert adapt_mean < flat_mean, (r, flat_mean, adapt_mean)

    print(f"rank {r}/{w} OK", flush=True)
    rabit.finalize()


if __name__ == "__main__":
    main()
