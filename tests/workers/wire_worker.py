"""Quantized-wire data-plane worker: float SUM allreduces through the
XLA plane with rabit_dataplane_wire set. Verifies (a) results are
within the wire format's error envelope of the exact sum, and (b) every
rank holds BIT-IDENTICAL bytes — the property that keeps the robust
engine's replay buffers consistent when the wire is compressed
(checked by allreducing MIN and MAX of a hash of the result).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402


def _check_round(rank: int, world: int, wire: str, it: int) -> None:
    # envelopes grow ~sqrt(world) (test_wire_envelope pins this at
    # p in {8, 64, 128}); int8 keeps a flat floor for small worlds
    rtol = {"bf16": 2e-2 * max(1.0, world / 8) ** 0.5,
            "int8": max(5e-2, 2e-2 * world ** 0.5)}.get(wire, 1e-6)
    rng = np.random.default_rng(40 + rank + 1000 * it)
    # big enough for the ring path and a whole number of int8 blocks
    n = world * 8192
    x = rng.standard_normal(n).astype(np.float32)
    got = rabit.allreduce(x, rabit.SUM)

    # exact expectation recomputed locally from every rank's seed
    want = np.zeros(n, np.float64)
    for r in range(world):
        want += np.random.default_rng(
            40 + r + 1000 * it).standard_normal(n)
    np.testing.assert_allclose(
        got, want, rtol=rtol, atol=rtol * np.abs(want).max(),
        err_msg=f"wire={wire} result outside error envelope (it {it})")
    if wire in ("bf16", "int8"):
        # visibly quantized: f32-exact results would mean the payload
        # fell below the tree/ring crossover and the wire never ran —
        # this check must not pass vacuously
        rel = float(np.max(np.abs(got - want)) / np.max(np.abs(want)))
        assert rel > 1e-6, \
            f"wire={wire} it {it}: f32-exact results (wire not engaged?)"

    import zlib
    digest = float(zlib.crc32(got.tobytes()))   # order-sensitive
    hi = rabit.allreduce(np.array([digest]), rabit.MAX)
    lo = rabit.allreduce(np.array([digest]), rabit.MIN)
    assert hi[0] == lo[0] == digest, \
        f"wire={wire} it {it}: ranks disagree byte-wise (replay " \
        f"contract broken — a respawned rank's replayed result must " \
        f"equal what survivors hold)"


def main() -> None:
    rabit.init()
    rank = rabit.get_rank()
    world = rabit.get_world_size()
    wire = os.environ.get("RABIT_DATAPLANE_WIRE", "none")
    n_iter = int(os.environ.get("N_ITER", "1"))

    # checkpointed loop (mock kills via argv exercise recovery: the
    # respawn's quantized-sum results come back through result-log
    # REPLAY and must be byte-equal to the survivors' copies)
    version, _ = rabit.load_checkpoint()
    for it in range(version, n_iter):
        _check_round(rank, world, wire, it)
        rabit.checkpoint({"it": it + 1})

    rabit.tracker_print(f"wire_worker rank {rank}/{world} wire={wire} ok")
    rabit.finalize()


if __name__ == "__main__":
    main()
