"""Elastic-membership cluster worker (tests/test_elastic_cluster.py).

A pure control-plane worker: speaks the tracker registration protocol
directly (no native engine, no jax) so the membership state machine is
exercised end to end across real processes — initial formation at the
target world, a scripted death, the survivors' in-job re-formation at
N-1, and the re-admission back to N at the next epoch boundary.

Roles (selected by RABIT_TASK_ID / KILL_TASK / RABIT_NUM_TRIAL):

- the victim's first attempt registers, acks the formed world, then
  dies hard (exit 1 — the launcher re-admits it, budget-exempt);
- the victim's relaunch reports its predecessor dead (the ``evict``
  wire command: a restarted process is first-party death evidence),
  waits until the survivors have re-formed the shrunk world, sends
  ``join`` (parking at the tracker until the epoch boundary), and on
  admission seeds its empty checkpoint store from its siblings'
  durable shards (adopt_latest_from_peers);
- survivors watch the membership doc between "rounds" and re-register
  whenever the tracker has made a decision their formed world has not
  absorbed — once for the shrink, once for the grow.

Every live member of an epoch durably checkpoints the SAME payload
(a pure function of the assignment epoch and world size), so the test
can assert bit-exactness across ranks and across the resize.
"""

import json
import os
import socket
import struct
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from rabit_tpu.engine.ckpt_store import CheckpointStore  # noqa: E402
from rabit_tpu.tracker import membership  # noqa: E402
from rabit_tpu.tracker.tracker import MAGIC  # noqa: E402

HOST = os.environ["RABIT_TRACKER_URI"]
PORT = int(os.environ["RABIT_TRACKER_PORT"])
TASK = os.environ["RABIT_TASK_ID"]
ATTEMPT = int(os.environ.get("RABIT_NUM_TRIAL", "0") or 0)
OUT = os.environ["ELASTIC_OUT"]
KILL_TASK = os.environ.get("KILL_TASK", "1")
TARGET = int(os.environ.get("ELASTIC_TARGET", "4"))
DEADLINE = time.monotonic() + float(os.environ.get("ELASTIC_DEADLINE", "90"))


def _send_u32(c, v):
    c.sendall(struct.pack("<I", v))


def _send_str(c, s):
    b = s.encode()
    _send_u32(c, len(b))
    c.sendall(b)


def _recv_all(c, n):
    out = b""
    while len(out) < n:
        chunk = c.recv(n - len(out))
        if not chunk:
            raise ConnectionError("closed")
        out += chunk
    return out


def _recv_u32(c):
    return struct.unpack("<I", _recv_all(c, 4))[0]


def _recv_str(c):
    return _recv_all(c, _recv_u32(c)).decode()


def register(cmd):
    """One full registration: returns (rank, world, epoch) after the
    ready ack. Blocks inside the tracker until the batch forms."""
    c = socket.create_connection((HOST, PORT), timeout=10)
    c.settimeout(60)
    _send_u32(c, MAGIC)
    _send_str(c, cmd)
    _send_str(c, TASK)
    _send_u32(c, ATTEMPT)
    _send_str(c, "127.0.0.1")
    _send_u32(c, 9100 + int(TASK))
    _send_u32(c, 0)   # flags: no data plane
    _send_str(c, "")  # no UDS twin
    rank = _recv_u32(c)
    world = _recv_u32(c)
    epoch = _recv_u32(c)
    _recv_str(c)      # coord_host
    _recv_u32(c)      # coord_port
    _recv_u32(c)      # single_host
    _recv_u32(c)      # parent
    for _ in range(_recv_u32(c)):
        _recv_u32(c)  # tree neighbor
    _recv_u32(c)      # ring_prev
    _recv_u32(c)      # ring_next
    for _ in range(_recv_u32(c)):
        _recv_u32(c)
        _recv_str(c)
        _recv_u32(c)
        _recv_str(c)
    _recv_u32(c)      # naccept
    _send_u32(c, 1)   # ready ack
    c.close()
    return rank, world, epoch


def predecessor_rank():
    """The stable rank the tracker assigned this task's DEAD
    incarnation. Ranks are handed out in registration-arrival order,
    not by task id — under load task "1" may well hold rank 2 — so
    evicting ``int(TASK)`` can hit a live survivor and wedge the
    world. The attempt-0 ``formed rank=R`` log line is the
    first-party record of the real assignment."""
    try:
        with open(os.path.join(OUT, f"r{TASK}.log")) as f:
            for ln in f.read().splitlines():
                if ln.startswith("formed rank="):
                    return int(ln.split("rank=")[1].split()[0])
    except OSError:
        pass
    return int(TASK)


def evict_self(rank):
    """Report the previous incarnation of this stable rank dead."""
    c = socket.create_connection((HOST, PORT), timeout=10)
    _send_u32(c, MAGIC)
    _send_str(c, "evict")
    _send_str(c, TASK)
    _send_u32(c, ATTEMPT)
    _send_str(c, json.dumps({"rank": rank, "reason": "restarted"}))
    ok = _recv_u32(c)
    c.close()
    return ok


def wait_for(pred, what):
    while True:
        assert time.monotonic() < DEADLINE, f"timed out waiting for {what}"
        doc = membership.fetch_world(HOST, PORT, TASK)
        if doc is not None and pred(doc):
            return doc
        time.sleep(0.05)


def log(msg):
    with open(os.path.join(OUT, f"r{TASK}.log"), "a") as f:
        f.write(msg + "\n")


def checkpoint_payload(epoch, world):
    """The deterministic 'model' every live member of an epoch writes:
    a pure function of the formed epoch and world size, so bit-exact
    agreement across ranks is assertable from the outside."""
    return json.dumps({"epoch": epoch, "world": world},
                      sort_keys=True).encode()


def main():
    store = CheckpointStore(os.path.join(OUT, "ckpt"), rank=int(TASK),
                            keep=2)
    if TASK == KILL_TASK and ATTEMPT == 0:
        rank, world, epoch = register("start")
        log(f"formed rank={rank} world={world} epoch={epoch}")
        log("dying")
        os._exit(1)

    if TASK == KILL_TASK:
        # relaunched victim: first-party death evidence, then park
        evict_self(predecessor_rank())
        log("evicted self")
        # the survivors must absorb the shrink before we re-admit, or
        # the next batch would form straight back at the target world
        wait_for(lambda d: d.get("epoch", 0) >= 2, "shrunk world")
        rank, world, epoch = register("join")
        log(f"rejoined rank={rank} world={world} epoch={epoch}")
        adopted = store.adopt_latest_from_peers()
        log(f"adopted v{adopted}")
        store.save(2, checkpoint_payload(epoch, world))
        log("done")
        return

    # survivor: form, absorb the shrink, absorb the grow
    rank, world, epoch = register("start")
    log(f"formed rank={rank} world={world} epoch={epoch}")
    wait_for(lambda d: d.get("evicted"), "eviction")
    rank, world, epoch = register("recover")
    log(f"reformed rank={rank} world={world} epoch={epoch}")
    store.save(1, checkpoint_payload(epoch, world))
    wait_for(lambda d: d.get("joining"), "parked joiner")
    rank, world, epoch = register("recover")
    log(f"reformed rank={rank} world={world} epoch={epoch}")
    store.save(2, checkpoint_payload(epoch, world))
    log("done")


if __name__ == "__main__":
    main()
