"""In-process native-resize cluster worker
(tests/test_native_resize_cluster.py, ISSUE 12).

A native-engine rank that survives an elastic shrink -> grow WITHOUT
its process ever exiting — the point of ``rabit.resize()``: before
this PR a world resize on the native engine meant dying and burning a
``max_attempts`` respawn; now it is an in-process relink.

Phases (rounds are a pure function of (round, world), so int64 sums
are exact and CRC streams are bit-comparable across runs):

- pre: all ranks form world N and stream ``PRE`` exact rounds;
- shrink (resize runs only): the victim reports ITSELF evicted over
  the ``evict`` wire command — its process stays alive — and the
  survivors absorb the shrink with ``rabit.resize("recover")``,
  streaming ``MID`` rounds at world N-1 while the victim waits;
- grow: the victim re-admits itself with ``rabit.resize("join")``
  (parked at the tracker until the epoch boundary; the survivors see
  the parked joiner and resize once more), and all N ranks stream
  ``POST`` rounds — which must be bit-identical to a fixed-world
  baseline that never resized.

Exit 0 only if every round on every path was exact.
"""

import json
import os
import socket
import struct
import sys
import time
import zlib

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402
from rabit_tpu.tracker import membership  # noqa: E402
from rabit_tpu.tracker.tracker import MAGIC  # noqa: E402

HOST = os.environ["RABIT_TRACKER_URI"]
PORT = int(os.environ["RABIT_TRACKER_PORT"])
TASK = os.environ.get("RABIT_TASK_ID", "?")
OUT = os.environ["RESIZE_OUT"]
KILL_TASK = os.environ.get("KILL_TASK", "1")
DO_RESIZE = os.environ.get("RESIZE_ENABLE", "") == "1"
DEADLINE = time.monotonic() + float(os.environ.get("RESIZE_DEADLINE", "90"))

PRE = range(0, 5)      # world N
MID = range(5, 8)      # world N-1 (survivors only)
POST = range(10, 15)   # world N again — compared against the baseline


def log(msg):
    with open(os.path.join(OUT, f"r{TASK}.log"), "a") as f:
        f.write(msg + "\n")


def do_round(tag, rnd):
    world, rank = rabit.get_world_size(), rabit.get_rank()
    a = np.arange(256, dtype=np.int64) * (rank + 1) + rnd
    out = rabit.allreduce(a, rabit.SUM)
    expect = (np.arange(256, dtype=np.int64)
              * (world * (world + 1) // 2) + rnd * world)
    np.testing.assert_array_equal(out, expect)
    log(f"{tag} round={rnd} world={world} "
        f"crc={zlib.crc32(out.tobytes()):08x}")


def evict_self(rank):
    """First-party death evidence for THIS rank — but the process
    stays alive, which is exactly what makes the later ``join`` an
    in-process re-admission instead of a respawn."""
    c = socket.create_connection((HOST, PORT), timeout=10)
    for chunk in (struct.pack("<I", MAGIC),):
        c.sendall(chunk)
    for s in ("evict", TASK):
        b = s.encode()
        c.sendall(struct.pack("<I", len(b)) + b)
    c.sendall(struct.pack("<I", 0))
    payload = json.dumps({"rank": rank, "reason": "resize-test"}).encode()
    c.sendall(struct.pack("<I", len(payload)) + payload)
    ok = struct.unpack("<I", c.recv(4))[0]
    c.close()
    return ok


def wait_for(pred, what):
    while True:
        assert time.monotonic() < DEADLINE, f"timed out waiting for {what}"
        doc = membership.fetch_world(HOST, PORT, TASK)
        if doc is not None and pred(doc):
            return doc
        time.sleep(0.05)


def main():
    rabit.init([a for a in sys.argv[1:] if "=" in a], engine="native")
    rank, world = rabit.get_rank(), rabit.get_world_size()
    assert rabit.is_distributed()
    log(f"formed rank={rank} world={world}")

    for rnd in PRE:
        do_round("pre", rnd)

    if DO_RESIZE:
        if TASK == KILL_TASK:
            assert evict_self(rabit.get_rank()) == 1
            log("evicted self (process alive)")
            # survivors must absorb the shrink before we park, or the
            # next batch forms straight back at the target world
            wait_for(lambda d: d.get("epoch", 0) >= 2, "shrunk world")
            rabit.resize("join")
            log(f"rejoined rank={rabit.get_rank()} "
                f"world={rabit.get_world_size()}")
        else:
            wait_for(lambda d: d.get("evicted"), "eviction")
            rabit.resize("recover")
            log(f"reformed rank={rabit.get_rank()} "
                f"world={rabit.get_world_size()}")
            for rnd in MID:
                do_round("mid", rnd)
            wait_for(lambda d: d.get("joining"), "parked joiner")
            rabit.resize("recover")
            log(f"reformed rank={rabit.get_rank()} "
                f"world={rabit.get_world_size()}")

    for rnd in POST:
        do_round("post", rnd)

    log("done")
    rabit.finalize()


if __name__ == "__main__":
    main()
