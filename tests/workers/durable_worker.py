"""Durable-checkpoint worker: iterates versioned checkpoints against a
``rabit_ckpt_dir`` store and asserts the resume point. Launched twice
by test_chaos_cluster.py — the second launch is a cold restart (every
process fresh, native version 0 everywhere) and must resume at the
version the fleet agrees on via the MAX/MIN/broadcast consensus.

argv: key=value params forwarded to the engine (rabit_ckpt_dir=...)
env:  N_TARGET (iterate until this version), EXPECT_VERSION (the
      version load_checkpoint must report on startup)
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402


def main() -> None:
    rabit.init([a for a in sys.argv[1:] if "=" in a])
    rank = rabit.get_rank()
    world = rabit.get_world_size()
    target = int(os.environ.get("N_TARGET", "3"))
    expect = int(os.environ.get("EXPECT_VERSION", "0"))

    version, model = rabit.load_checkpoint()
    assert version == expect, \
        f"rank {rank}: resumed at v{version}, expected v{expect}"
    if version == 0:
        model = {"step": 0}
    # model contents are a pure function of the version: a resume with
    # the wrong (or torn) payload fails here, not just the wrong number
    assert model["step"] == version, (model, version)

    for it in range(version, target):
        s = rabit.allreduce(np.full(8, float(rank + 1)), rabit.SUM)
        np.testing.assert_allclose(s, np.full(8, world * (world + 1) / 2))
        model["step"] = it + 1
        rabit.checkpoint(model)
        assert rabit.version_number() == it + 1, \
            f"version {rabit.version_number()} after checkpoint {it + 1}"

    rabit.tracker_print(f"durable_worker rank {rank}/{world} reached "
                        f"v{rabit.version_number()} OK")
    rabit.finalize()


if __name__ == "__main__":
    main()
