"""Device-plane-only failure worker: the world stays healthy (no process
dies) but the data-plane callback raises once on every rank
(RABIT_DATAPLANE_FAIL_AT), mapping to kReset -> reconnect -> epoch
advance -> device-world re-formation. Asserts the collective stream
stays correct through it and that the epoch really advanced (the proof
the engine recovered rather than wedged — VERDICT r2 weak #6).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402


def main() -> None:
    reformed = []
    rabit.init(engine="robust_xla")
    engine = rabit._engine  # test-only peek at the active engine
    engine.set_world_reformed_callback(lambda e: reformed.append(e))
    rank = rabit.get_rank()
    world = rabit.get_world_size()
    epoch0 = engine.world_epoch

    for it in range(6):
        out = rabit.allreduce(np.full(31, float(rank + it), np.float32),
                              rabit.SUM)
        want = sum(float(r + it) for r in range(world))
        np.testing.assert_allclose(out, np.full(31, want),
                                   err_msg=f"SUM wrong at iter {it}")

    # the scripted failure fired on a healthy world: the epoch must have
    # advanced (links rewired) and the device world re-formed at least
    # twice (initial + post-failure)
    if os.environ.get("RABIT_DATAPLANE_FAIL_AT"):
        assert engine.world_epoch > epoch0, \
            f"epoch did not advance: {epoch0} -> {engine.world_epoch}"
        assert len(reformed) >= 2, f"re-formations seen: {reformed}"
    rabit.finalize()
    print(f"DATAPLANE-FAIL-OK rank={rank} reformed={len(reformed)}")


if __name__ == "__main__":
    main()
