"""Self-verifying multi-process worker (reference guide/basic.cc +
test/basic.cc style): every rank computes the expected reduction
analytically and asserts elementwise equality."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

if os.environ.get("RABIT_DATAPLANE") == "xla":
    # tests drive the device plane on the CPU backend (gloo); must be
    # configured before any computation touches the default backend
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402


def main() -> None:
    # pin the ring crossover explicitly: the same-host DEFAULT now
    # prefers the streaming tree, and this worker exists to cover BOTH
    # collective algorithms (the m=50000 ops below exercise the ring).
    # argv key=value params still pass through (the default init reads
    # them from sys.argv; appending must not drop them).
    rabit.init([a for a in sys.argv[1:] if "=" in a] +
               ["rabit_reduce_ring_mincount=32768"],
               engine=os.environ.get("WORKER_ENGINE", "native"))
    rank = rabit.get_rank()
    world = rabit.get_world_size()
    assert rabit.is_distributed()

    # tree path (small buffer)
    n = 117
    a = np.arange(n, dtype=np.float32) + rank
    out = rabit.allreduce(a, rabit.MAX)
    np.testing.assert_allclose(out, np.arange(n) + (world - 1))

    s = rabit.allreduce(np.full(n, rank + 1, dtype=np.int64), rabit.SUM)
    np.testing.assert_array_equal(s, np.full(n, world * (world + 1) // 2))

    # ring path (element count above reduce_ring_mincount)
    m = 50000
    big = np.full(m, float(rank + 1), dtype=np.float64)
    out = rabit.allreduce(big, rabit.SUM)
    np.testing.assert_allclose(out, np.full(m, world * (world + 1) / 2))

    mn = rabit.allreduce(np.full(m, rank, dtype=np.int32), rabit.MIN)
    np.testing.assert_array_equal(mn, np.zeros(m, np.int32))

    # bitor
    flags = np.full(8, 1 << rank, dtype=np.uint32)
    out = rabit.allreduce(flags, rabit.BITOR)
    np.testing.assert_array_equal(out, np.full(8, (1 << world) - 1))

    # object broadcast from every root
    for root in range(world):
        obj = rabit.broadcast({"root": root, "blob": b"x" * 1000}
                              if rank == root else None, root)
        assert obj["root"] == root and len(obj["blob"]) == 1000

    rabit.tracker_print(f"basic_worker rank {rank}/{world} OK")
    rabit.finalize()


if __name__ == "__main__":
    main()
