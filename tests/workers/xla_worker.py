"""Multi-process XLA-engine worker: rendezvous through the JAX
coordination service (the reference tracker's role, SURVEY §2.3), then
allreduce over the cross-process device mesh.

argv: <process_id> <num_processes> <coordinator_port> [mode]

mode (default "base") selects the exercise:
  base        ring + tree allreduce paths and the pickle broadcast
  wire-bf16 / wire-int8
              quantized-wire allreduce over the real gloo fabric with
              the mincount gate forced open; every rank additionally
              proves bit-identity of its result via a CRC allreduce
  bidir / swing
              rabit_reduce_method config plumbed end-to-end (engine ->
              env export -> dispatch -> per-shard schedule)
  hier        two-level hierarchical schedule on a 4-process world
              forced into 2 simulated hosts (rabit_hier_group=2):
              engine-path SUM/MAX bit-exact across dtypes plus a
              direct device-level ring-vs-hier comparison
  bcast       large-array + non-zero-root broadcast variants
"""

import os
import sys
import zlib

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402


def _assert_ranks_identical(arr: np.ndarray, r: int) -> None:
    """Every rank must hold byte-identical results (the replay-buffer
    contract): allreduce the CRC both ways and require agreement."""
    crc = np.array([zlib.crc32(np.ascontiguousarray(arr).tobytes())],
                   np.int64)
    hi = rabit.allreduce(crc, rabit.MAX)
    lo = rabit.allreduce(crc, rabit.MIN)
    assert hi[0] == lo[0] == crc[0], (r, int(crc[0]), int(hi[0]), int(lo[0]))


def main() -> None:
    pid, nproc, port = sys.argv[1], sys.argv[2], sys.argv[3]
    mode = sys.argv[4] if len(sys.argv) > 4 else "base"
    cfg = ["rabit_engine=xla",
           f"rabit_coordinator=127.0.0.1:{port}",
           f"rabit_num_processes={nproc}",
           f"rabit_process_id={pid}"]
    if mode.startswith("wire-"):
        # force the size gate open: the point here is the codec over the
        # real fabric, not the crossover policy
        cfg += [f"rabit_dataplane_wire={mode[5:]}",
                "rabit_dataplane_wire_mincount=0"]
    elif mode in ("bidir", "swing"):
        cfg += [f"rabit_reduce_method={mode}"]
    elif mode == "hier":
        # 4 procs forced into 2 simulated hosts of 2: every engine
        # collective below runs the two-level schedule on real gloo
        cfg += ["rabit_reduce_method=hier", "rabit_hier_group=2"]
    rabit.init(cfg)
    r, w = rabit.get_rank(), rabit.get_world_size()
    assert w == int(nproc), (r, w)

    if mode == "bcast":
        # two-phase pickle broadcast: large payload, non-zero root
        big = np.arange(200_000, dtype=np.float32) * 3.5
        got = rabit.broadcast(big if r == 0 else None, 0)
        assert np.array_equal(got, big), (r, got[:3])
        root = w - 1
        obj = rabit.broadcast({"root": root} if r == root else None, root)
        assert obj == {"root": root}, (r, obj)
    elif mode.startswith("wire-"):
        rng = np.random.default_rng(13)
        xs = rng.standard_normal(300_000).astype(np.float32)
        got = rabit.allreduce(xs + r, rabit.SUM)
        want = xs * w + sum(range(w))
        rtol = 2e-2 if mode == "wire-bf16" else 5e-2
        np.testing.assert_allclose(got, want, rtol=rtol,
                                   atol=rtol * np.abs(want).max())
        _assert_ranks_identical(got, r)
    elif mode == "hier":
        # engine path: integer-valued payloads make SUM association-free,
        # so the two-level schedule must be BIT-exact against the
        # analytic answer for every dtype — float included
        base = np.arange(9973) % 101
        for dt in (np.int32, np.int64, np.float32, np.float64):
            got = rabit.allreduce((base + r).astype(dt), rabit.SUM)
            assert got.dtype == np.dtype(dt), (r, got.dtype)
            assert np.array_equal(got, (base * w + sum(range(w))
                                        ).astype(dt)), (r, dt, got[:4])
            got = rabit.allreduce((base + r).astype(dt), rabit.MAX)
            assert np.array_equal(got, (base + (w - 1)).astype(dt)), \
                (r, dt, got[:4])
        # float SUM on arbitrary values: allclose + CRC rank-identity
        # (SPMD: every rank runs one program, so bytes must agree)
        rng = np.random.default_rng(13)
        fs = rng.standard_normal(50_000).astype(np.float32)
        got = rabit.allreduce(fs + r, rabit.SUM)
        np.testing.assert_allclose(got, fs * w + sum(range(w)), rtol=1e-5,
                                   atol=1e-4)
        _assert_ranks_identical(got, r)

        # device level: hier vs flat ring on the SAME staged global
        # array over the real gloo fabric, bit-for-bit (integer-valued
        # data again, odd length to exercise the pad/slice path)
        from jax.sharding import NamedSharding, PartitionSpec as P
        from rabit_tpu.parallel.collectives import device_allreduce
        eng = rabit._engine
        mesh = eng._mesh
        assert eng._groups == ((0, 1), (2, 3)), eng._groups

        def stage(arr):
            local = jax.device_put(arr.reshape(1, -1),
                                   mesh.local_devices[0])
            return jax.make_array_from_single_device_arrays(
                (w, arr.size), NamedSharding(mesh, P("proc")), [local])

        prng = np.random.default_rng(100 + r)
        vals = prng.integers(-50, 50, 4099)
        for op in (rabit.SUM, rabit.MAX):
            for dt in (np.int32, np.float32):
                arr = vals.astype(dt)
                ring = np.asarray(device_allreduce(
                    stage(arr), mesh, op, axis="proc",
                    method="ring").addressable_data(0)).reshape(-1)
                hier = np.asarray(device_allreduce(
                    stage(arr), mesh, op, axis="proc", method="hier",
                    groups=((0, 1), (2, 3))).addressable_data(0)
                ).reshape(-1)
                assert hier.dtype == ring.dtype, (op, dt, hier.dtype)
                assert np.array_equal(ring, hier), \
                    (r, op, dt, ring[:4], hier[:4])
    elif mode in ("bidir", "swing"):
        big = rabit.allreduce(np.full(150_000, float(r + 1), np.float32),
                              rabit.SUM)
        assert np.allclose(big, sum(range(1, w + 1))), (r, big[:3])
        small = rabit.allreduce(np.arange(64, dtype=np.int32) + r,
                                rabit.SUM)
        want = np.arange(64) * w + sum(range(w))
        assert np.array_equal(small, want), (r, small[:4])
    else:
        # large payload -> ring (ppermute) path
        big = rabit.allreduce(np.full(100_000, float(r + 1), np.float32),
                              rabit.SUM)
        assert np.all(big == sum(range(1, w + 1))), (r, big[:3])

        # small payload -> tree (psum) path
        small = rabit.allreduce(np.arange(8, dtype=np.int32) + r, rabit.MAX)
        assert np.all(small == np.arange(8) + (w - 1)), (r, small)

        # two-phase pickle broadcast
        obj = rabit.broadcast({"from": 0, "v": [1, 2, 3]} if r == 0 else None,
                              0)
        assert obj == {"from": 0, "v": [1, 2, 3]}, (r, obj)

    print(f"rank {r}/{w} OK", flush=True)
    rabit.finalize()


if __name__ == "__main__":
    main()
