"""Multi-process XLA-engine worker: rendezvous through the JAX
coordination service (the reference tracker's role, SURVEY §2.3), then
allreduce over the cross-process device mesh.

argv: <process_id> <num_processes> <coordinator_port>
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402


def main() -> None:
    pid, nproc, port = sys.argv[1], sys.argv[2], sys.argv[3]
    rabit.init(["rabit_engine=xla",
                f"rabit_coordinator=127.0.0.1:{port}",
                f"rabit_num_processes={nproc}",
                f"rabit_process_id={pid}"])
    r, w = rabit.get_rank(), rabit.get_world_size()
    assert w == int(nproc), (r, w)

    # large payload -> ring (ppermute) path
    big = rabit.allreduce(np.full(100_000, float(r + 1), np.float32),
                          rabit.SUM)
    assert np.all(big == sum(range(1, w + 1))), (r, big[:3])

    # small payload -> tree (psum) path
    small = rabit.allreduce(np.arange(8, dtype=np.int32) + r, rabit.MAX)
    assert np.all(small == np.arange(8) + (w - 1)), (r, small)

    # two-phase pickle broadcast
    obj = rabit.broadcast({"from": 0, "v": [1, 2, 3]} if r == 0 else None, 0)
    assert obj == {"from": 0, "v": [1, 2, 3]}, (r, obj)

    print(f"rank {r}/{w} OK", flush=True)
    rabit.finalize()


if __name__ == "__main__":
    main()
