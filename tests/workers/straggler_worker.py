"""Shutdown-fence straggler scenario (reference AllreduceRobust::Shutdown
two-phase consensus exit, allreduce_robust.cc:54-67).

Every rank runs a checkpoint loop, then N_TAIL collectives AFTER the
final checkpoint — their results exist only in the in-memory result log.
The victim rank self-kills between its last collective and finalize(): the
survivors reach finalize() with nothing left to compute, while the
victim's respawn must reload the final checkpoint and replay every tail
seq from the finishers' result logs. Without the shutdown fence the
finishers drop their links immediately and strand the straggler; with it
they loop at the pseudo-checkpoint fence serving the load + replays until
the whole world reaches the fence.

argv: key=value engine params (rabit_dataplane=... for the XLA plane)
env:  N_ITER (default 3), N_TAIL (default 3), VICTIM (default 1),
      RABIT_NUM_TRIAL (set by the tracker launcher: respawn attempt #)
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

if os.environ.get("RABIT_DATAPLANE") == "xla":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402


def main() -> None:
    rabit.init()
    rank = rabit.get_rank()
    world = rabit.get_world_size()
    n_iter = int(os.environ.get("N_ITER", "3"))
    n_tail = int(os.environ.get("N_TAIL", "3"))
    victim = int(os.environ.get("VICTIM", "1"))
    attempt = int(os.environ.get("RABIT_NUM_TRIAL", "0"))

    version, model = rabit.load_checkpoint()
    if version == 0:
        model = {"iter": 0}
    assert model["iter"] == version, (model, version)

    for it in range(model["iter"], n_iter):
        s = rabit.allreduce(np.full(17, float(rank + 1 + it), np.float64),
                            rabit.SUM)
        np.testing.assert_allclose(
            s, np.full(17, world * (world + 1) / 2 + world * it),
            err_msg=f"SUM wrong at iter {it}")
        model["iter"] = it + 1
        rabit.checkpoint(model)

    # Tail collectives past the last checkpoint: on a respawn these seqs
    # can only be satisfied by replay from ranks already in finalize().
    for s in range(n_tail):
        out = rabit.allreduce(
            np.full(31, float((rank + 1) * (s + 1)), np.float64), rabit.SUM)
        np.testing.assert_allclose(
            out, np.full(31, world * (world + 1) / 2 * (s + 1)),
            err_msg=f"tail SUM wrong at seq {s} (attempt {attempt})")

    if rank == victim and attempt == 0:
        # all collectives done, finalize not yet called: the other ranks
        # have nothing left to compute and head straight into shutdown
        print(f"straggler_worker rank {rank} self-kill pre-finalize",
              file=sys.stderr, flush=True)
        os._exit(255)

    rabit.tracker_print(
        f"straggler_worker rank {rank}/{world} attempt {attempt} done")
    rabit.finalize()


if __name__ == "__main__":
    main()
