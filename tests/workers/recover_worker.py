"""Self-verifying fault-tolerance worker (reference test/model_recover.cc
+ local_recover.cc): iterates versioned checkpoints, verifies every
collective analytically each iteration, and survives scripted kills
(mock=rank,version,seqno,ntrial argv params) through tracker respawn +
result replay + checkpoint recovery.

argv: key=value params forwarded to the engine (mock=..., etc.)
env:  N_ITER (default 6), WITH_LOCAL=1 for local-checkpoint mode,
      LAZY=1 for LazyCheckPoint
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

if os.environ.get("RABIT_DATAPLANE") == "xla":
    # tests drive the device plane on the CPU backend (gloo); must be
    # configured before any computation touches the default backend
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402


def verify_iteration(rank: int, world: int, it: int) -> None:
    n = 97
    # MAX with lazy prepare_fun (reference model_recover.cc uses a
    # prepare that fills the buffer)
    marker = []

    def prep(d):
        marker.append(True)
        d[:] = np.arange(n, dtype=np.float32) + rank + it

    a = np.zeros(n, dtype=np.float32)
    out = rabit.allreduce(a, rabit.MAX, prepare_fun=prep)
    np.testing.assert_allclose(out, np.arange(n) + (world - 1) + it,
                               err_msg=f"MAX wrong at iter {it}")

    s = rabit.allreduce(np.full(n, float(rank + 1), np.float64), rabit.SUM)
    np.testing.assert_allclose(s, np.full(n, world * (world + 1) / 2),
                               err_msg=f"SUM wrong at iter {it}")

    obj = rabit.broadcast({"it": it, "payload": list(range(it * 3))},
                          it % world)
    assert obj["it"] == it and len(obj["payload"]) == it * 3


def main() -> None:
    rabit.init()  # mock entries in argv auto-select the mock engine
    rank = rabit.get_rank()
    world = rabit.get_world_size()
    n_iter = int(os.environ.get("N_ITER", "6"))
    with_local = os.environ.get("WITH_LOCAL") == "1"
    lazy = os.environ.get("LAZY") == "1"

    if with_local:
        version, model, local = rabit.load_checkpoint(with_local=True)
        if version == 0:
            model, local = {"iter": 0}, {"rank_data": rank * 1000}
        assert local["rank_data"] == rank * 1000, \
            f"local checkpoint corrupt: {local}"
    else:
        version, model = rabit.load_checkpoint()
        if version == 0:
            model = {"iter": 0}
        local = None
    assert model["iter"] == version, (model, version)

    for it in range(model["iter"], n_iter):
        verify_iteration(rank, world, it)
        model["iter"] = it + 1
        if lazy:
            rabit.lazy_checkpoint(model)
        elif with_local:
            local["rank_data"] = rank * 1000
            rabit.checkpoint(model, local_model=local)
        else:
            rabit.checkpoint(model)
        assert rabit.version_number() == it + 1

    rabit.tracker_print(f"recover_worker rank {rank}/{world} "
                        f"finished {n_iter} iters OK")
    rabit.finalize()


if __name__ == "__main__":
    main()
