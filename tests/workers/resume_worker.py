"""Tracker-resume cluster worker (tests/test_tracker_resume_cluster.py).

A native-engine rank that keeps computing THROUGH a tracker crash: the
data plane rides worker-worker links, so once the world is formed the
tracker's death must cost nothing but control-plane reachability. Each
round allreduces a deterministic int64 payload and logs its CRC — the
stream is bit-comparable against an uninterrupted baseline run.

Between rounds the worker leans on the control plane the way a real
job does:

- a :class:`SkewMonitor` poller (RABIT_SKEW_TRACKER pointed at the
  launcher's tracker address, i.e. the chaos proxy) polls every
  ``RABIT_SKEW_POLL_MS`` — these accepts are what trigger the chaos
  ``tracker_kill`` inside its window, then trip the poller's circuit
  breaker during the outage, then re-arm it against the resumed
  incarnation (the ISSUE 10 satellite fix), firing ``present_resume``
  + ``reannounce`` exactly once;
- breaker transitions are logged (``breaker tripped`` / ``breaker
  rearmed``) so the test can assert the reconnect actually happened.

The worker exits 0 only if every round's allreduce was exact — any
rank lost mid-run would wedge or corrupt the collectives and fail the
whole cluster.
"""

import os
import sys
import time
import zlib

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402

OUT = os.environ["RESUME_OUT"]
ROUNDS = int(os.environ.get("RESUME_ROUNDS", "60"))
ROUND_SLEEP_S = float(os.environ.get("RESUME_ROUND_SLEEP_MS", "200")) / 1e3
TASK = os.environ.get("RABIT_TASK_ID", "?")


def log(msg):
    with open(os.path.join(OUT, f"r{TASK}.log"), "a") as f:
        f.write(msg + "\n")


def main() -> None:
    # the skew poller is this worker's steady control-plane heartbeat;
    # point it at the launcher-provided tracker address (the chaos
    # proxy, when chaos fronts the tracker)
    host = os.environ.get("RABIT_TRACKER_URI", "")
    port = os.environ.get("RABIT_TRACKER_PORT", "")
    if host and port:
        os.environ["RABIT_SKEW_TRACKER"] = f"{host}:{port}"

    rabit.init([a for a in sys.argv[1:] if "=" in a], engine="native")
    rank = rabit.get_rank()
    world = rabit.get_world_size()
    assert rabit.is_distributed()
    log(f"formed rank={rank} world={world}")

    from rabit_tpu.telemetry import skew
    mon = skew.monitor()
    mon.current()  # starts the poller (RABIT_SKEW_TRACKER is set)
    was_tripped = False

    for rnd in range(ROUNDS):
        # pure function of (round, world): int64 sums are exact, so the
        # CRC stream is bit-identical no matter what the control plane
        # went through mid-run
        a = (np.arange(256, dtype=np.int64) * (rank + 1) + rnd)
        out = rabit.allreduce(a, rabit.SUM)
        expect = (np.arange(256, dtype=np.int64)
                  * (world * (world + 1) // 2) + rnd * world)
        np.testing.assert_array_equal(out, expect)
        log(f"round={rnd} crc={zlib.crc32(out.tobytes()):08x}")

        tripped = mon.breaker_state()["tripped"]
        if tripped and not was_tripped:
            log("breaker tripped")
        elif was_tripped and not tripped:
            log("breaker rearmed")
        was_tripped = tripped
        time.sleep(ROUND_SLEEP_S)

    log("done")
    rabit.finalize()


if __name__ == "__main__":
    main()
