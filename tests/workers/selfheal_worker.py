"""Self-healing data-plane cluster worker
(tests/test_selfheal_cluster.py, ISSUE 13).

A native-engine rank streaming CRC-framed collectives
(``rabit_frame_crc=1``) while chaos link proxies corrupt or tear the
wire underneath it. Every round is a pure function of (round, world),
so int64 sums are exact and the logged CRC streams are bit-comparable
against a fault-free baseline run — the whole point: hop-local frame
retransmission and link resurrection must heal the wire without the
application seeing ANY difference (no wrong bytes, no exit, no respawn,
no eviction).

Payloads are deliberately large (512 KiB sums): the 16-byte frame
headers are a vanishing fraction of the stream, so seeded bitflips
land in CRC-protected payload bytes, exercising the reject+retransmit
rung rather than the reset escalation.

Exit 0 only if every collective on every rank was exact.
"""

import os
import sys
import zlib

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402

TASK = os.environ.get("RABIT_TASK_ID", "?")
OUT = os.environ["SELFHEAL_OUT"]
N_SUM = int(os.environ.get("N_SUM", "6"))
N_BCAST = int(os.environ.get("N_BCAST", "2"))
COUNT = int(os.environ.get("SUM_COUNT", "65536"))  # x8 bytes = 512 KiB


def log(msg):
    with open(os.path.join(OUT, f"r{TASK}.log"), "a") as f:
        f.write(msg + "\n")


def main():
    rabit.init([a for a in sys.argv[1:] if "=" in a], engine="native")
    rank, world = rabit.get_rank(), rabit.get_world_size()
    assert rabit.is_distributed()
    log(f"formed rank={rank} world={world}")

    for rnd in range(N_SUM):
        a = np.arange(COUNT, dtype=np.int64) * (rank + 1) + rnd
        out = rabit.allreduce(a, rabit.SUM)
        expect = (np.arange(COUNT, dtype=np.int64)
                  * (world * (world + 1) // 2) + rnd * world)
        np.testing.assert_array_equal(out, expect)
        log(f"sum round={rnd} world={world} "
            f"crc={zlib.crc32(out.tobytes()):08x}")

    for rnd in range(N_BCAST):
        blob = (np.arange(32768, dtype=np.int64) + rnd).tobytes()  # 256 KiB
        got = rabit.broadcast(blob if rank == 0 else None, 0)
        assert got == blob, f"bcast round {rnd} corrupted"
        log(f"bcast round={rnd} world={world} crc={zlib.crc32(got):08x}")

    log("done")
    rabit.finalize()


if __name__ == "__main__":
    main()
