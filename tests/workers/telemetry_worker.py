"""Worker for the tracker-aggregation telemetry test: runs both
collective paths with telemetry on, so finalize exports per-rank
artifacts (``RABIT_TELEMETRY_EXPORT``) and ships the summary to the
tracker for the end-of-run fleet table."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402
import rabit_tpu as rabit  # noqa: E402


def main() -> None:
    rabit.init([a for a in sys.argv[1:] if "=" in a] +
               ["rabit_telemetry=1", "rabit_reduce_ring_mincount=32768"],
               engine=os.environ.get("WORKER_ENGINE", "native"))
    rank = rabit.get_rank()
    world = rabit.get_world_size()
    assert rabit.is_distributed()

    # tree path (small) and ring path (large) so the fleet table carries
    # at least two distinct counter rows
    small = rabit.allreduce(np.full(117, rank + 1, np.float32), rabit.SUM)
    np.testing.assert_allclose(small, np.full(117, world * (world + 1) / 2))
    big = rabit.allreduce(np.full(50000, float(rank + 1), np.float64),
                          rabit.SUM)
    np.testing.assert_allclose(big, np.full(50000, world * (world + 1) / 2))

    rabit.finalize()  # exports artifacts + ships the metrics summary


if __name__ == "__main__":
    main()
