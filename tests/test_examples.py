"""The shipped examples and C++ test/perf binaries must actually run —
single-process, multi-process under the tracker, and under fault
injection (the reference's guide/*.cc,*.py double as its smoke tests)."""

import os
import subprocess
import sys

import pytest

from tests.test_integration import LIB, ROOT

BUILD = os.path.join(ROOT, "native", "build")

pytestmark = pytest.mark.skipif(
    not os.path.isfile(LIB), reason="native core not built")


def launch_prog(nworkers, prog_argv, timeout=120):
    from rabit_tpu.tracker.launch import launch
    return launch(nworkers, list(prog_argv), timeout=timeout)


def test_api_test_binary():
    # single-process C++ header-API unit tests
    out = subprocess.run([os.path.join(BUILD, "api_test")],
                         capture_output=True, timeout=60)
    assert out.returncode == 0, out.stderr.decode()
    assert b"all ok" in out.stdout


@pytest.mark.parametrize("ex", ["basic", "broadcast", "lazy_allreduce",
                                "custom_reducer"])
def test_cc_example(ex):
    assert launch_prog(3, [os.path.join(BUILD, f"example_{ex}")]) == 0


def test_cc_example_with_failure():
    # one scripted death mid-loop; the respawned worker must catch up
    assert launch_prog(
        3, [os.path.join(BUILD, "example_basic"), "mock=1,2,0,0"]) == 0


@pytest.mark.parametrize("ex", ["basic", "broadcast", "lazy_allreduce"])
def test_py_example(ex):
    assert launch_prog(
        3, [sys.executable, os.path.join(ROOT, "examples", "py",
                                         f"{ex}.py")]) == 0


@pytest.mark.parametrize("wire", ["bf16", "int8"])
def test_py_example_quantized_wire(wire):
    # argv alone suffices: init() parses key=value args and exports
    # RABIT_DATAPLANE_WIRE to the engine (engine/native.py _export_env).
    # The demo payload sits below the default wire size gate and the
    # committed dispatch table routes it to the (wire-less) tree, so the
    # example pins the ring schedule and forces the gate open — the
    # documented way to make quantization visible at demo sizes
    rc = launch_prog(
        3, [sys.executable,
            os.path.join(ROOT, "examples", "py", "quantized_wire.py"),
            "rabit_dataplane=xla", "rabit_dataplane_minbytes=0",
            "rabit_reduce_method=ring", "rabit_dataplane_wire_mincount=0",
            f"rabit_dataplane_wire={wire}"], timeout=180)
    assert rc == 0


def test_speed_test_small():
    # perf harness runs and reports (tiny size: this is a smoke test)
    assert launch_prog(
        3, [os.path.join(BUILD, "speed_test"), "ndata=1000", "nrep=3"]) == 0
