"""Quantized-wire error envelope at scale (VERDICT r4 #6).

tests/test_collectives.py pins the envelope at world 8; these runs pin
it on LARGE virtual meshes — p=64 and p=128 — where quantization error
has accumulated over p-1 ring hops. The measured growth is ~sqrt(p)
(bf16: 0.014 @ p=8 -> ~0.037 @ p=64; int8: ~0.054 @ p=128), and the
asserted bound is the same ``2e-2 * sqrt(p)`` the multichip dryrun
allows (__graft_entry__.py) and doc/guide.md documents.

Each case needs its own device count, which XLA fixes at backend init —
so the measurement runs in a subprocess with its own XLA_FLAGS (the
conftest pins this process to 8 virtual devices).
"""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROBE = """\
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {root!r})
from rabit_tpu.parallel.collectives import device_allreduce, SUM
from tests.test_collectives import make_mesh, shard_over

p = {p}
wire = {wire!r}
mesh = make_mesh(p)
rng = np.random.default_rng(7)
n = p * 256  # per-rank chunk = one int8 block
xs = rng.standard_normal((p, n)).astype(np.float32)
want = xs.sum(axis=0)
out = device_allreduce(shard_over(mesh, xs), mesh, SUM,
                       method="ring", wire=wire)
got = np.asarray(out)
rel = np.abs(got - want).max() / np.abs(want).max()
assert rel < 2e-2 * np.sqrt(p), (wire, p, rel)
# quantization must actually be engaged: an exact result would mean
# the wire path silently fell back to f32
assert rel > 1e-4, (wire, p, rel)
# every rank bit-identical — the replay/recovery contract holds at
# scale, not only at world 8
shards = [np.asarray(out.addressable_data(i)) for i in range(p)]
for i in range(1, p):
    assert np.array_equal(shards[0], shards[i]), (wire, i)
print(f"ENVELOPE-OK {{wire}} p={{p}} rel={{rel:.4f}}")
"""


@pytest.mark.parametrize("p", [64, 128])
@pytest.mark.parametrize("wire", ["bf16", "int8"])
def test_wire_envelope_at_scale(p, wire, tmp_path):
    prog = tmp_path / "probe.py"
    prog.write_text(PROBE.format(root=ROOT, p=p, wire=wire))
    env = dict(os.environ)
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={p}"
    env["JAX_PLATFORMS"] = "cpu"
    # hermetic: the axon sitecustomize can hang startup when the TPU
    # relay is wedged, and this is a pure-CPU measurement
    env["PYTHONPATH"] = ROOT
    out = subprocess.run([sys.executable, str(prog)], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, (out.stdout[-500:], out.stderr[-1500:])
    assert f"ENVELOPE-OK {wire} p={p}" in out.stdout, out.stdout
