"""WAL snapshot compaction (ISSUE 19): offline --compact equivalence
against a full replay, live snapshot trigger + resume, replication
across a compaction (snapshot frame seq jump), and promotion of a
standby whose replicated journal contains a snapshot."""

import json
import shutil
import socket
import struct
import time

import pytest

from rabit_tpu.tracker import jobs as jobs_mod
from rabit_tpu.tracker import wal as wal_mod
from rabit_tpu.tracker.standby import StandbyTracker
from rabit_tpu.tracker.tracker import (
    MAGIC as WIRE_MAGIC, Tracker, fold_records, snapshot_state)
from rabit_tpu.tracker.wal import SNAPSHOT_KIND, WriteAheadLog


# --------------------------------------------------------------- helpers

def _send_u32(s, v):
    s.sendall(struct.pack("<I", v))


def _send_str(s, txt):
    b = txt.encode()
    _send_u32(s, len(b))
    s.sendall(b)


def _recv_all(s, n):
    out = b""
    while len(out) < n:
        chunk = s.recv(n - len(out))
        if not chunk:
            raise ConnectionError("closed")
        out += chunk
    return out


def _wire_cmd(tr, cmd, task_id="0", payload=None):
    """One raw tracker command round-trip; returns the u32 reply."""
    c = socket.create_connection((tr.host, tr.port), timeout=10)
    _send_u32(c, WIRE_MAGIC)
    _send_str(c, cmd)
    _send_str(c, task_id)
    _send_u32(c, 0)
    if payload is not None:
        _send_str(c, payload)
    out = struct.unpack("<I", _recv_all(c, 4))[0]
    c.close()
    return out


def _endpoint(tr, task, port):
    assert _wire_cmd(tr, "endpoint", task, json.dumps(
        {"host": "127.0.0.1", "port": int(port),
         "rank": int(task.rsplit("/", 1)[-1])})) == 1


def _form(tr, tasks):
    conns = [jobs_mod.wire_register(tr.host, tr.port, t) for t in tasks]
    return sorted(jobs_mod.wire_read_assignment(c) for c in conns)


def _resume(dead, root, **kw):
    deadline = time.monotonic() + 10
    while True:
        try:
            return Tracker(dead.nworkers, host=dead.host, port=dead.port,
                           wal_dir=root, resume=True, **kw)
        except OSError:
            assert time.monotonic() < deadline, "port never freed"
            time.sleep(0.05)


def _busy_tracker(root, monkeypatch):
    """A multi-job elastic tracker with real history: two formed
    worlds, an eviction, endpoint announces, a closed job."""
    monkeypatch.setenv("RABIT_MULTI_JOB", "1")
    monkeypatch.setenv("RABIT_ELASTIC", "1")
    tr = Tracker(2, wal_dir=root, elastic=True, multi_job=True).start()
    assert jobs_mod.submit(tr.host, tr.port, "jobA", 2,
                           elastic=True)["ok"] == 1
    assert jobs_mod.submit(tr.host, tr.port, "jobB", 1)["ok"] == 1
    assert _form(tr, ["jobA/0", "jobA/1"]) == [(0, 2, 1), (1, 2, 1)]
    assert _form(tr, ["jobB/0"]) == [(0, 1, 1)]
    _endpoint(tr, "jobA/0", 9100)
    _endpoint(tr, "jobA/1", 9101)
    assert _wire_cmd(tr, "evict", "jobA/x", json.dumps(
        {"rank": 1, "reason": "test"})) == 1
    jobs_mod.wire_shutdown(tr.host, tr.port, "jobB/0")
    deadline = time.monotonic() + 10
    while tr.job("jobB").open:
        assert time.monotonic() < deadline, "jobB never closed"
        time.sleep(0.02)
    return tr


# ----------------------------------------------- offline --compact


def test_offline_compaction_replays_to_same_state(tmp_path, monkeypatch):
    """THE acceptance bar: snapshot + tail replays to the same tracker
    state as the full journal (fingerprinted via snapshot_state)."""
    root_a = str(tmp_path / "full")
    tr = _busy_tracker(root_a, monkeypatch)
    tr.crash()
    root_b = str(tmp_path / "compacted")
    shutil.copytree(root_a, root_b)

    out = wal_mod.compact_dir(root_b, nworkers=2, elastic=True)
    assert out["folded"] > 5 and out["seq"] == out["folded"] + 1
    log = WriteAheadLog(root_b)
    records = log.open(resume=True)
    log.close()
    assert records[0][0] == SNAPSHOT_KIND and len(records) == 1
    assert log.base == out["folded"]

    full = _resume(tr, root_a, multi_job=True, elastic=True)
    full.start()
    try:
        snap = Tracker(2, wal_dir=root_b, resume=True,
                       multi_job=True, elastic=True).start()
        try:
            with full._lock, snap._lock:
                a, b = snapshot_state(full), snapshot_state(snap)
            assert a == b
            # and the state is the real history, not vacuously empty
            assert a["jobs"]["jobA"]["member"]["evicted"] == [1]
            assert a["jobs"]["jobA"]["endpoints"]["1"]["port"] == 9101
            assert a["jobs"]["jobB"]["closed"] is True
            assert snap.job("jobA")._epoch == full.job("jobA")._epoch == 1
        finally:
            snap.stop()
    finally:
        full.stop()


def test_fold_records_matches_wal_replay(tmp_path, monkeypatch):
    """fold_records over the raw journal equals the live tracker's own
    serialized state at crash time (write-ahead: the journal IS the
    state)."""
    root = str(tmp_path / "wal")
    tr = _busy_tracker(root, monkeypatch)
    with tr._lock:
        live = snapshot_state(tr)
    tr.crash()
    folded = fold_records(WriteAheadLog(root).replay(),
                          nworkers=2, elastic=True)
    assert folded == live


# ------------------------------------------------- live snapshots


def test_live_snapshot_trigger_resume_and_inspect(tmp_path, monkeypatch):
    """rabit_wal_snapshot_every compacts a LIVE journal: the root is
    rewritten as snapshot + tail, --inspect reports it, and a crash ->
    resume replays the compacted journal to the same world."""
    monkeypatch.setenv("RABIT_WAL_SNAPSHOT_EVERY", "6")
    root = str(tmp_path / "wal")
    tr = Tracker(2, wal_dir=root).start()
    try:
        assert _form(tr, ["0", "1"]) == [(0, 2, 1), (1, 2, 1)]
        for i in range(8):
            _endpoint(tr, "0", 9200 + i)
        deadline = time.monotonic() + 10
        while tr.snapshot_seq() == 0:
            assert time.monotonic() < deadline, "never snapshotted"
            time.sleep(0.02)
        doc = wal_mod.inspect_journal(root)
        assert doc["snapshot_seq"] == tr.snapshot_seq()
        assert doc["base"] == doc["snapshot_seq"] - 1
        assert doc["snapshot_age_s"] is not None
        assert doc["last_seq"] >= doc["snapshot_seq"]
        with tr._lock:
            live = snapshot_state(tr)
        tr.crash()
        res = _resume(tr, root)
        res.start()
        try:
            assert res._ranks == {"0": 0, "1": 1}
            assert res._epoch == 1 and res.restarts == 1
            with res._lock:
                got = snapshot_state(res)
            got["restarts"] = live["restarts"]  # resume bumped it
            assert got == live
        finally:
            res.stop()
    finally:
        tr.stop()


def test_snapshot_off_by_default(tmp_path):
    """Knob unset: no snapshot records, byte-identical journal plane."""
    root = str(tmp_path / "wal")
    tr = Tracker(2, wal_dir=root).start()
    try:
        assert _form(tr, ["0", "1"]) == [(0, 2, 1), (1, 2, 1)]
        assert tr.snapshot_seq() == 0
    finally:
        tr.stop()
    assert all(k != SNAPSHOT_KIND
               for k, _d in WriteAheadLog(root).replay())


# -------------------------------------------- replication + promotion


def test_promotion_through_live_snapshot(tmp_path, monkeypatch):
    """A standby that replicated a mid-stream snapshot frame promotes
    to the same world: snapshot + tail rides the repl stream in-order
    and replays through Tracker(resume=True) at promotion."""
    monkeypatch.setenv("RABIT_WAL_SNAPSHOT_EVERY", "5")
    lease_ms = 400
    tr = sb = None
    try:
        tr = Tracker(2, wal_dir=str(tmp_path / "leader"),
                     lease_ms=lease_ms).start()
        sb = StandbyTracker(tr.host, tr.port, 2,
                            wal_dir=str(tmp_path / "standby"),
                            lease_ms=lease_ms, quiet=True).start()
        assert _form(tr, ["0", "1"]) == [(0, 2, 1), (1, 2, 1)]
        for i in range(6):
            _endpoint(tr, "1", 9300 + i)
        deadline = time.monotonic() + 10
        while tr.snapshot_seq() == 0:
            assert time.monotonic() < deadline, "never snapshotted"
            time.sleep(0.02)
        _endpoint(tr, "0", 9400)   # a tail record PAST the snapshot
        deadline = time.monotonic() + 10
        while sb.acked_seq < tr.repl_stats()["seq"]:
            assert time.monotonic() < deadline, "replication lagged"
            time.sleep(0.02)
        with tr._lock:
            live = snapshot_state(tr)
        tr.crash()
        t0 = time.monotonic()
        while not sb.promoted():
            assert time.monotonic() - t0 < 10, "standby never promoted"
            time.sleep(0.02)
        res = sb.tracker
        assert res._ranks == {"0": 0, "1": 1} and res._epoch == 1
        assert res._endpoints["0"]["port"] == 9400
        assert res._endpoints["1"]["port"] == 9305
        with res._lock:
            got = snapshot_state(res)
        # promotion stamps restarts/lease/failover on top of the
        # replicated history; the journaled world must match exactly
        assert got["jobs"] == live["jobs"]
    finally:
        if sb is not None:
            sb.stop()
        if tr is not None:
            tr.stop()


def test_follower_resync_across_precompacted_leader(tmp_path,
                                                    monkeypatch):
    """A leader RESUMED from a compacted journal (base > 0) serves a
    fresh follower the snapshot root first; the follower's journal
    adopts the seq jump and promotion replays snapshot + tail."""
    lease_ms = 400
    root = str(tmp_path / "leader")
    tr = Tracker(2, wal_dir=root).start()
    assert _form(tr, ["0", "1"]) == [(0, 2, 1), (1, 2, 1)]
    _endpoint(tr, "0", 9500)
    tr.stop()
    wal_mod.compact_dir(root, nworkers=2)
    res = sb = None
    try:
        res = _resume(tr, root, lease_ms=lease_ms)
        res.start()
        assert res._repl_base > 0
        sb = StandbyTracker(res.host, res.port, 2,
                            wal_dir=str(tmp_path / "standby"),
                            lease_ms=lease_ms, quiet=True).start()
        _endpoint(res, "1", 9501)   # post-compaction tail record
        deadline = time.monotonic() + 10
        while sb.acked_seq < res.repl_stats()["seq"]:
            assert time.monotonic() < deadline, "replication lagged"
            time.sleep(0.02)
        assert sb.acked_seq > res._repl_base
        res.crash()
        t0 = time.monotonic()
        while not sb.promoted():
            assert time.monotonic() - t0 < 10, "standby never promoted"
            time.sleep(0.02)
        prom = sb.tracker
        assert prom._ranks == {"0": 0, "1": 1} and prom._epoch == 1
        assert prom._endpoints["0"]["port"] == 9500   # from the snapshot
        assert prom._endpoints["1"]["port"] == 9501   # from the tail
    finally:
        if sb is not None:
            sb.stop()
        (res or tr).stop()


# ----------------------------------------------------- CLI surface


def test_wal_cli_compact_and_inspect(tmp_path, capsys):
    root = str(tmp_path / "wal")
    w = WriteAheadLog(root)
    w.open()
    w.record("assign", task="0", rank=0)
    w.record("epoch", epoch=1)
    w.close()
    assert wal_mod._main(["--compact", root, "--nworkers", "2"]) == 0
    out = capsys.readouterr().out
    assert "compacted 2 records into a snapshot at seq 3" in out
    assert wal_mod._main(["--inspect", root]) == 0
    out = capsys.readouterr().out
    assert "snapshot at seq 3" in out and "+0 tail records" in out
    doc = wal_mod.inspect_journal(root)
    assert doc["base"] == 2 and doc["snapshot_seq"] == 3
    assert doc["tail_records"] == 0
    # the folded state carries the journaled rank
    records = WriteAheadLog(root).replay()
    state = records[0][1]["state"]
    assert state["jobs"]["default"]["ranks"] == {"0": 0}
    assert state["jobs"]["default"]["epoch"] == 1


def test_compact_dir_refuses_missing_journal(tmp_path):
    with pytest.raises(wal_mod.WalError):
        wal_mod.compact_dir(str(tmp_path / "nope"))
