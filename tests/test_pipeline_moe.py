"""Pipeline (pp) and expert (ep) parallelism: parity vs dense oracles on
the virtual 8-device CPU mesh, gradients, and a small training loop."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from rabit_tpu.parallel import (
    make_mesh, make_pipeline_fn, place_pipeline_params, stack_stage_params,
    make_moe_fn, init_moe_params, place_moe_params, moe_reference)
from rabit_tpu.parallel.collectives import shard_map
from rabit_tpu.parallel import moe as moe_mod

D = 16


# ---------------------------------------------------------------------------
# pipeline
# ---------------------------------------------------------------------------

def _stage_fn(prm, x):
    return jnp.tanh(x @ prm["w"] + prm["b"])


def _stage_params(rng, n_stages):
    out = []
    for i in range(n_stages):
        k = jax.random.fold_in(rng, i)
        out.append({
            "w": jax.random.normal(k, (D, D)) * (1.0 / np.sqrt(D)),
            "b": jnp.zeros((D,)),
        })
    return out


@pytest.mark.parametrize("n_stages,n_micro", [(4, 8), (8, 8), (8, 3)])
def test_pipeline_forward_parity(n_stages, n_micro):
    mesh = make_mesh(n_stages, ("pp",))
    rng = jax.random.PRNGKey(0)
    stages = _stage_params(rng, n_stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (n_micro, 4, D))

    want = x
    for prm in stages:
        want = jax.vmap(lambda xx, p=prm: _stage_fn(p, xx))(want)

    fn = make_pipeline_fn(mesh, _stage_fn)
    got = fn(place_pipeline_params(mesh, stages), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_gradient_parity():
    """Backward through the pipeline (reverse pipeline) matches dense
    stage-by-stage autodiff."""
    n_stages, n_micro = 4, 6
    mesh = make_mesh(n_stages, ("pp",))
    stages = _stage_params(jax.random.PRNGKey(2), n_stages)
    x = jax.random.normal(jax.random.PRNGKey(3), (n_micro, 4, D))

    def dense_loss(stages):
        h = x
        for i in range(n_stages):
            h = jax.vmap(lambda xx: _stage_fn(
                jax.tree.map(lambda s: s[i], stages), xx))(h)
        return (h * h).sum()

    stacked = stack_stage_params(stages)
    want = jax.grad(dense_loss)(stacked)

    fn = make_pipeline_fn(mesh, _stage_fn)

    def sharded_loss(stacked):
        y = fn(stacked, x)
        return (y * y).sum()

    got = jax.grad(sharded_loss)(place_pipeline_params(mesh, stages))
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=1e-4, atol=1e-4, err_msg=k)


def test_pipeline_stage_count_mismatch_rejected():
    """8 stages on a 4-rank pp axis must fail loudly, not silently apply
    every other stage."""
    mesh = make_mesh(4, ("pp",))
    stages = _stage_params(jax.random.PRNGKey(8), 8)
    x = jax.random.normal(jax.random.PRNGKey(9), (4, 4, D))
    with pytest.raises(ValueError, match="one stage per rank"):
        make_pipeline_fn(mesh, _stage_fn)(
            place_pipeline_params(mesh, stages), x)


def test_pipeline_single_stage():
    mesh = make_mesh(1, ("pp",))
    stages = _stage_params(jax.random.PRNGKey(4), 1)
    x = jax.random.normal(jax.random.PRNGKey(5), (3, 4, D))
    got = make_pipeline_fn(mesh, _stage_fn)(
        place_pipeline_params(mesh, stages), x)
    want = jax.vmap(lambda xx: _stage_fn(stages[0], xx))(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# MoE
# ---------------------------------------------------------------------------

def test_moe_forward_parity_no_drops():
    """With generous capacity nothing is dropped, so the ep-sharded MoE
    equals the dense per-token oracle."""
    p = 8
    mesh = make_mesh(p, ("ep",))
    params = init_moe_params(jax.random.PRNGKey(0), d_model=D, d_ff=32,
                             n_experts=p)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, D))
    fn = make_moe_fn(mesh, capacity_factor=float(p))  # capacity = n_loc
    xs = jax.device_put(x, NamedSharding(mesh, P("ep")))
    y, aux = fn(place_moe_params(mesh, params), xs)
    want = moe_reference(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_moe_capacity_drops_tokens():
    """With capacity factor << 1 some tokens must be dropped (output
    exactly zero for them) — the standard Switch overflow semantics."""
    p = 4
    mesh = make_mesh(p, ("ep",))
    params = init_moe_params(jax.random.PRNGKey(2), d_model=D, d_ff=32,
                             n_experts=p)
    x = jax.random.normal(jax.random.PRNGKey(3), (64, D))
    fn = make_moe_fn(mesh, capacity_factor=0.25)
    xs = jax.device_put(x, NamedSharding(mesh, P("ep")))
    y, _ = fn(place_moe_params(mesh, params), xs)
    dropped = np.all(np.asarray(y) == 0.0, axis=-1)
    assert dropped.any(), "expected some dropped tokens at cf=0.25"
    assert not dropped.all()


def test_moe_gradients_flow():
    """Router, experts, and inputs all get finite nonzero grads through
    the two all-to-alls."""
    p = 4
    mesh = make_mesh(p, ("ep",))
    params = init_moe_params(jax.random.PRNGKey(4), d_model=D, d_ff=32,
                             n_experts=p)
    x = jax.random.normal(jax.random.PRNGKey(5), (32, D))
    fn = make_moe_fn(mesh, capacity_factor=4.0)
    placed = place_moe_params(mesh, params)
    xs = jax.device_put(x, NamedSharding(mesh, P("ep")))

    def loss(params, x):
        y, aux = fn(params, x)
        return (y * y).sum() + 0.01 * aux

    g_params, g_x = jax.grad(loss, argnums=(0, 1))(placed, xs)
    for k, g in g_params.items():
        assert np.isfinite(np.asarray(g)).all(), k
        assert float(jnp.abs(g).max()) > 0, k
    assert np.isfinite(np.asarray(g_x)).all()


def test_moe_expert_count_mismatch_rejected():
    mesh = make_mesh(4, ("ep",))
    params = init_moe_params(jax.random.PRNGKey(6), d_model=D, d_ff=32,
                             n_experts=8)
    x = jnp.zeros((16, D))
    with pytest.raises(ValueError, match="one expert per rank"):
        make_moe_fn(mesh)(params, x)


def test_moe_ffn_direct_mismatch_rejected():
    """Calling the exported per-shard moe_ffn directly with n_experts a
    multiple of the axis size must fail loudly, not silently interleave
    expert slots."""
    mesh = make_mesh(4, ("ep",))
    params = init_moe_params(jax.random.PRNGKey(8), d_model=D, d_ff=32,
                             n_experts=8)
    f = shard_map(
        functools.partial(moe_mod.moe_ffn, axis_name="ep"),
        mesh=mesh,
        in_specs=(moe_mod.moe_param_specs("ep"), P("ep")),
        out_specs=(P("ep"), P()))
    placed = place_moe_params(mesh, params)
    x = jax.device_put(jnp.zeros((16, D)), NamedSharding(mesh, P("ep")))
    with pytest.raises(ValueError, match="one expert per rank"):
        jax.jit(f)(placed, x)


def test_moe_training_specializes_experts():
    """A few SGD steps on a clusterable input distribution reduce loss —
    the ep pipeline trains end-to-end."""
    p = 4
    mesh = make_mesh(p, ("ep",))
    params = init_moe_params(jax.random.PRNGKey(7), d_model=D, d_ff=32,
                             n_experts=p)
    rng = np.random.default_rng(0)
    centers = rng.standard_normal((p, D)).astype(np.float32) * 2
    xs_np = (centers[rng.integers(0, p, 128)] +
             rng.standard_normal((128, D)).astype(np.float32) * 0.1)
    target = np.roll(xs_np, 1, axis=1)
    fn = make_moe_fn(mesh, capacity_factor=4.0)
    placed = place_moe_params(mesh, params)
    sh = NamedSharding(mesh, P("ep"))
    xj = jax.device_put(jnp.asarray(xs_np), sh)
    tj = jax.device_put(jnp.asarray(target), sh)

    @jax.jit
    def step(params):
        def loss(params):
            y, aux = fn(params, xj)
            return ((y - tj) ** 2).mean() + 0.01 * aux
        l, g = jax.value_and_grad(loss)(params)
        return jax.tree.map(lambda p, gg: p - 0.3 * gg, params, g), l

    losses = []
    for _ in range(10):
        placed, l = step(placed)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.8, losses
