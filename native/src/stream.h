// In-memory byte streams — the serialization substrate for checkpoints.
// Capability parity with reference include/rabit/internal/io.h
// (MemoryFixSizeBuffer / MemoryBufferStream over dmlc::SeekStream), but
// designed around std::string buffers with explicit cursors since the
// dmlc-core dependency is not part of this project.
#ifndef RT_STREAM_H_
#define RT_STREAM_H_

#include <cstring>
#include <string>

#include "log.h"

namespace rt {

// Growable in-memory stream (reference MemoryBufferStream, io.h:60-103).
class MemStream {
 public:
  MemStream() = default;
  explicit MemStream(std::string data) : buf_(std::move(data)) {}

  void Write(const void* ptr, size_t n) {
    if (pos_ + n > buf_.size()) buf_.resize(pos_ + n);
    memcpy(&buf_[pos_], ptr, n);
    pos_ += n;
  }
  size_t Read(void* ptr, size_t n) {
    size_t avail = buf_.size() - pos_;
    if (n > avail) n = avail;
    memcpy(ptr, &buf_[pos_], n);
    pos_ += n;
    return n;
  }
  template <typename T>
  void WritePod(const T& v) { Write(&v, sizeof(T)); }
  template <typename T>
  T ReadPod() {
    T v{};
    RT_CHECK(Read(&v, sizeof(T)) == sizeof(T), "stream underrun");
    return v;
  }
  void WriteStr(const std::string& s) {
    WritePod<uint64_t>(s.size());
    Write(s.data(), s.size());
  }
  std::string ReadStr() {
    uint64_t n = ReadPod<uint64_t>();
    RT_CHECK(pos_ + n <= buf_.size(), "stream underrun");
    std::string s = buf_.substr(pos_, n);
    pos_ += n;
    return s;
  }
  void Seek(size_t pos) { pos_ = pos; }
  size_t Tell() const { return pos_; }
  const std::string& Buffer() const { return buf_; }
  std::string&& TakeBuffer() { return std::move(buf_); }

 private:
  std::string buf_;
  size_t pos_ = 0;
};

}  // namespace rt

#endif  // RT_STREAM_H_
