// RobustComm — fault-tolerant collective engine.
//
// Capability parity with the reference AllreduceRobust
// (src/allreduce_robust.{h,cc}): per-iteration result log replayed to
// laggards/restarted workers, packed-word consensus rounds, in-memory
// version-prefixed global checkpoint recoverable from any holder,
// ring-replicated local checkpoints, lazy checkpoint, two-phase commit,
// bootstrap cache for pre-LoadCheckpoint collectives.
//
// Fresh design (vs the reference's MsgPassing/ShortestDist routing,
// allreduce_robust-inl.h:33-166): recovery routing is a holder-rooted
// tree broadcast — the consensus round elects the lowest-ranked holder
// (packed max-key allreduce) and the payload rides the ordinary
// TryBroadcast state machine. Same O(size·depth) cost over TCP, far
// less machinery, and it maps directly onto an XLA collective when the
// data plane moves on-device.
#ifndef RT_ROBUST_H_
#define RT_ROBUST_H_

#include <map>
#include <string>
#include <vector>

#include "comm.h"

namespace rt {

// Inherits Comm's thread model (comm.h): engine-thread state, no locks.
// Recovery state (recover_counter_, checkpoint buffers, replay cache)
// mutates only inside collectives on the owning thread; the watchdog's
// reform rung lands via net.h's annotated interrupt plane and surfaces
// here as NetResult::kInterrupt, so CheckAndRecover still runs on the
// engine thread. TSan builds (RT_SANITIZE=thread) verify this holds.
class RobustComm : public Comm {
 public:
  void Allreduce(void* buf, size_t elem_size, size_t count, ReduceFn reducer,
                 PrepareFn prepare = nullptr, void* prepare_arg = nullptr,
                 const char* cache_key = "",
                 int dtype = -1, int op = -1) override;
  void Broadcast(void* buf, size_t size, int root,
                 const char* cache_key = "") override;
  int LoadCheckpoint(std::string* global, std::string* local) override;
  void Checkpoint(const std::string& global, const std::string& local)
      override;
  void LazyCheckpoint(const std::string* global) override;
  void Init(int argc, const char* const* argv) override;
  void Shutdown() override;
  void InitAfterException() override;
  void Resize(const char* cmd = "recover") override;

 public:
  // consensus word (reference ActionSummary, allreduce_robust.h:200-298):
  // OR-reduced flags + min seqno + min ~seqno (carries the max)
  struct ActionPod {
    uint32_t flags = 0;
    uint32_t seqno = 0;
    uint32_t neg_seqno = 0;
  };

 protected:
  enum Flag : uint32_t {
    kLoadCheck = 1u << 0,
    kCheckPoint = 1u << 1,
    kCheckAck = 1u << 2,
    kLoadBootstrap = 1u << 3,
  };

  // hook for the mock engine's scripted kill points
  virtual void OnEngineCall(const char* fn) { (void)fn; }

  // One consensus round + serving. Returns true when THIS rank's pending
  // op (seq `my_seq`, result size `size`) was satisfied by replay; false
  // when the rank should execute the op itself (reference RecoverExec,
  // allreduce_robust.cc:1046-1199).
  bool RecoverExec(void* buf, size_t size, uint32_t flag, uint32_t my_seq,
                   const std::string& cache_key = "");

  void CheckAndRecover(NetResult res);

  // robust small allreduce driving the ActionPod rounds ONLY; retries
  // through link resets. Everything nested inside a round must be a
  // non-retrying Try* call that unwinds errors back to RecoverExec, so
  // after any failure every rank realigns at the same (idempotent)
  // ActionPod allreduce — a retry nested inside serving would leave
  // ranks in differently-shaped collectives on shared links.
  void ConsensusAllreduce(void* buf, size_t elem_size, size_t count,
                          ReduceFn fn);
  // non-retrying elect of max (key, world-rank) across ranks
  NetResult TryElect(uint64_t key, uint64_t* out_key, int* out_rank);
  // one OR-reduced need-bitmask round; fills the agreed per-rank vector
  NetResult AgreeNeed(bool mine, std::vector<uint8_t>* need,
                      std::vector<uint8_t>* mask_scratch);
  NetResult TryServeLoadCheckpoint();
  NetResult TryServeReplay(uint32_t seq, void* buf, size_t size,
                           bool i_am_requester);
  NetResult TryServeBootstrap(void* buf, size_t size, bool mine,
                              const std::string& cache_key, bool* served);
  NetResult TryReplicateLocal();
  // log the just-completed op's result for replay (or, for pre-load
  // bootstrap ops, into the signature-keyed cache without a seqno)
  void FinishOp(const void* buf, size_t size, const std::string& key,
                bool bootstrap);

  // result log since last checkpoint (reference ResultBuffer,
  // allreduce_robust.h:300-364), thinned by rotating ownership: rank r
  // stores seqno s only when s % result_round_ == r % result_round_,
  // result_round_ = max(1, world/num_global_replica) (reference
  // allreduce_robust.cc:43-47,185-189), so each result has
  // ~num_global_replica holders and replay survives that many deaths
  std::map<uint32_t, std::string> result_log_;
  uint32_t seq_counter_ = 0;
  int num_global_replica_ = 5;
  uint32_t result_round_ = 1;

  // bootstrap cache: pre-LoadCheckpoint collectives keyed by caller
  // signature (reference allreduce_robust.cc:89-141)
  bool bootstrap_cache_enabled_ = false;
  bool before_first_load_ = true;
  std::map<std::string, std::string> bootstrap_cache_;

  std::string global_ckpt_;
  const std::string* lazy_global_ = nullptr;  // LazyCheckPoint pointer
  std::string local_ckpt_;
  // ring-replicated copies of predecessors' local checkpoints:
  // replica_local_[i] = local state of rank (rank_ - 1 - i + P) % P
  std::vector<std::string> replica_local_;
  int num_local_replica_ = 0;  // locked in on first checkpoint-with-local
  bool local_mode_decided_ = false;
  bool local_expected_ = false;

  int recover_counter_ = 0;
  // rabit_collective_retries: bound on in-collective recovery loops
  // (was a hardcoded 1000) — the retry rung of the escalation ladder
  int collective_retries_ = 1000;
};

}  // namespace rt

#endif  // RT_ROBUST_H_
