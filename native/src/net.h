// TCP networking layer for the host-side control/data plane.
// Capability parity with reference include/rabit/internal/socket.h
// (SockAddr/TCPSocket/PollHelper, socket.h:50-533), redesigned: Linux-only
// (no WinSock shims), RAII connections, explicit Result codes instead of
// errno-taxonomy scattered through the engine (reference
// allreduce_base.h:224-263), and progress-oriented TrySend/TryRecv used
// by the poll-driven collectives.
#ifndef RT_NET_H_
#define RT_NET_H_

#include <poll.h>

#include <cstdint>
#include <string>
#include <vector>

#include "rt_thread_annotations.h"

namespace rt {

// Outcome of a socket operation; the recovery layer keys off kReset
// (peer death) vs kError (local/socket failure) — reference
// ReturnType {kSuccess,kConnReset,kRecvZeroLen,kSockError}.
// kInterrupt is not a socket outcome: poll loops synthesize it when an
// out-of-band interrupt (RequestInterrupt) asks the collective to bail
// out to the recovery layer, which treats it like kReset.
enum class NetResult { kOk, kAgain, kReset, kError, kInterrupt };

// CRC-32 (IEEE/zlib polynomial, bit-reflected) over ``n`` bytes —
// matches Python's zlib.crc32 so frames checked here can be
// cross-checked by the test battery without a second implementation.
uint32_t Crc32(const void* data, size_t n);
// Incremental form for checksumming discontiguous regions as one
// stream (frame scale-sidecar + payload): Begin -> Feed... -> End
// equals one Crc32 over the concatenation.
uint32_t Crc32Begin();
uint32_t Crc32Feed(uint32_t state, const void* data, size_t n);
uint32_t Crc32End(uint32_t state);

// Out-of-band interrupt plane: a watchdog (any thread) raises the
// flag; collective poll loops observe it and return kInterrupt so the
// robust layer can run its global-reset recovery instead of spinning
// on a wedged link. File-scope (NOT per-comm/thread) on purpose — the
// raiser is a monitor thread that holds no engine handle.
//
// ``reason`` is a provenance tag ("watchdog_reform", a test name, …)
// carried alongside the flag: the raiser and the consumer are on
// different threads, so it lives under its own mutex (the flag itself
// stays a lone atomic — poll loops check it per iteration and must not
// take a lock on the hot path). The last reason is sticky: recovery
// logging reads it after the flag was consumed.
void RequestInterrupt(const std::string& reason = "");
bool TakeInterrupt();   // consume-and-clear; false when no request
// most recent RequestInterrupt reason ("" if never raised); sticky —
// reading does not clear, so post-recovery logs can attribute the reset
std::string LastInterruptReason();

class TcpConn {
 public:
  TcpConn() = default;
  explicit TcpConn(int fd) : fd_(fd) {}
  TcpConn(const TcpConn&) = delete;
  TcpConn& operator=(const TcpConn&) = delete;
  TcpConn(TcpConn&& o) noexcept { *this = std::move(o); }
  TcpConn& operator=(TcpConn&& o) noexcept;
  ~TcpConn() { Close(); }

  static TcpConn Connect(const std::string& host, int port,
                         int retries = 30, int delay_ms = 200);
  // Same-host fast path: connect to the abstract-namespace unix socket
  // a Listener advertised as ``token`` (tracker-relayed). Returns an
  // invalid conn (ok() == false) instead of throwing when no such
  // socket exists in this network namespace — callers fall back to
  // TCP. Because tokens are random per listener (not derived from the
  // port), a cross-host or cross-netns attempt cannot accidentally
  // reach an unrelated worker that shares the port number.
  static TcpConn ConnectLocal(const std::string& token);
  // hostname -> dotted-quad, throwing on failure: callers that retry
  // Connect can resolve ONCE up front so a permanently bad name fails
  // fast instead of being re-resolved per attempt
  static std::string ResolveHost(const std::string& host);

  bool ok() const { return fd_ >= 0; }
  int fd() const { return fd_; }
  void Close();
  void SetNonBlocking(bool on);
  void SetNoDelay();
  void SetKeepAlive();
  // Bounded blocking receive: like RecvAll but gives up (returning
  // false, conn state unspecified) when no progress happens within
  // ``timeout_ms``. Used only during the link-resurrection handshake
  // so a half-open redial cannot wedge a rank forever.
  bool RecvAllTimeout(void* data, size_t n, int timeout_ms);

  // Blocking full-buffer ops (bootstrap/tracker path).
  void SendAll(const void* data, size_t n);
  void RecvAll(void* data, size_t n);
  void SendU32(uint32_t v);
  uint32_t RecvU32();
  void SendStr(const std::string& s);   // u32 length prefix
  std::string RecvStr();

  // Progress ops for nonblocking collectives: move up to n bytes,
  // return bytes moved, or -1 cast via NetResult out-param.
  ssize_t TrySend(const void* data, size_t n, NetResult* res);
  ssize_t TryRecv(void* data, size_t n, NetResult* res);

 private:
  int fd_ = -1;
};

// Listening socket with automatic port scan (reference TryBindHost,
// allreduce_base.cc:306-324). Alongside TCP it listens on an
// abstract-namespace unix socket keyed by the TCP port, so same-host
// peers can skip the loopback TCP stack (~2x the large-payload
// throughput; OpenMPI's sm BTL showed the gap in SOCKET_VS_MPI_*).
// Abstract sockets need no filesystem cleanup and die with the
// process — recovery-safe.
class Listener {
 public:
  // binds the first free port in [port_start, port_start + ntrial);
  // with_local=false skips the UDS twin (rabit_local_uds=0 — A/B
  // measurement and an escape hatch)
  void Bind(int port_start, int ntrial = 1000, bool with_local = true);
  TcpConn Accept();   // whichever family is ready first
  // Accept bounded by ``timeout_ms``; returns an invalid conn
  // (ok() == false) on timeout. Link resurrection uses this so the
  // accepting side of a dead link waits only its redial budget before
  // escalating to the full ReconnectLinks ladder.
  TcpConn AcceptTimeout(int timeout_ms);
  int port() const { return port_; }
  // Random per-listener name of the UDS twin ("" when disabled or
  // bind failed). Workers advertise it through the tracker; peers that
  // can resolve it in their netns are by construction same-host.
  const std::string& local_token() const { return token_; }
  int fd() const { return fd_; }
  void Close();
  ~Listener() { Close(); }

 private:
  int fd_ = -1;
  int ufd_ = -1;  // abstract-namespace UDS twin; -1 when unavailable
  int port_ = 0;
  std::string token_;
};

// poll(2) wrapper (reference PollHelper, socket.h:440-533).
class Poller {
 public:
  void WatchRead(int fd);
  void WatchWrite(int fd);
  // returns number of ready fds; <0 on error; 0 on timeout
  int Wait(int timeout_ms = -1);
  bool CanRead(int fd) const;
  bool CanWrite(int fd) const;
  void Clear() { fds_.clear(); }

 private:
  std::vector<pollfd> fds_;
};

std::string GetHostName();

}  // namespace rt

#endif  // RT_NET_H_
