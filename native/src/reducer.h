// Elementwise reduction dispatch: (op enum x dtype enum) -> concrete
// function. Capability parity with the reference's op functors
// (rabit-inl.h:66-102) and the C-ABI double dispatch (c_api.cc:37-122),
// including the BitOR-on-float rejection (c_api.cc:26-35). Wire enums
// match the reference so the Python binding stays compatible.
#ifndef RT_REDUCER_H_
#define RT_REDUCER_H_

#include <cstddef>
#include <cstdint>

#include "log.h"

namespace rt {

enum Op : int { kMax = 0, kMin = 1, kSum = 2, kBitOR = 3 };

enum DType : int {
  kInt8 = 0, kUInt8 = 1, kInt32 = 2, kUInt32 = 3,
  kInt64 = 4, kUInt64 = 5, kFloat32 = 6, kFloat64 = 7,
  // TPU-native extensions (Python side stages these through the XLA
  // engine; host reduction treats f16/bf16 as unsupported for now)
};

inline size_t DTypeSize(int dtype) {
  switch (dtype) {
    case kInt8: case kUInt8: return 1;
    case kInt32: case kUInt32: return 4;
    case kInt64: case kUInt64: return 8;
    case kFloat32: return 4;
    case kFloat64: return 8;
    default: Fail(StrFormat("unknown dtype enum %d", dtype));
  }
}

// dst[i] = op(dst[i], src[i])
typedef void (*ReduceFn)(void* dst, const void* src, size_t count);

namespace detail {

template <typename T> struct MaxOp {
  static void Run(void* d, const void* s, size_t n) {
    T* dst = static_cast<T*>(d);
    const T* src = static_cast<const T*>(s);
    for (size_t i = 0; i < n; ++i) if (src[i] > dst[i]) dst[i] = src[i];
  }
};
template <typename T> struct MinOp {
  static void Run(void* d, const void* s, size_t n) {
    T* dst = static_cast<T*>(d);
    const T* src = static_cast<const T*>(s);
    for (size_t i = 0; i < n; ++i) if (src[i] < dst[i]) dst[i] = src[i];
  }
};
template <typename T> struct SumOp {
  static void Run(void* d, const void* s, size_t n) {
    T* dst = static_cast<T*>(d);
    const T* src = static_cast<const T*>(s);
    for (size_t i = 0; i < n; ++i) dst[i] += src[i];
  }
};
template <typename T> struct OrOp {
  static void Run(void* d, const void* s, size_t n) {
    T* dst = static_cast<T*>(d);
    const T* src = static_cast<const T*>(s);
    for (size_t i = 0; i < n; ++i) dst[i] |= src[i];
  }
};

template <typename T>
ReduceFn PickArith(int op) {
  switch (op) {
    case kMax: return MaxOp<T>::Run;
    case kMin: return MinOp<T>::Run;
    case kSum: return SumOp<T>::Run;
    default: return nullptr;
  }
}

template <typename T>
ReduceFn PickInt(int op) {
  if (op == kBitOR) return OrOp<T>::Run;
  return PickArith<T>(op);
}

}  // namespace detail

inline ReduceFn GetReducer(int op, int dtype) {
  ReduceFn fn = nullptr;
  switch (dtype) {
    case kInt8:   fn = detail::PickInt<int8_t>(op); break;
    case kUInt8:  fn = detail::PickInt<uint8_t>(op); break;
    case kInt32:  fn = detail::PickInt<int32_t>(op); break;
    case kUInt32: fn = detail::PickInt<uint32_t>(op); break;
    case kInt64:  fn = detail::PickInt<int64_t>(op); break;
    case kUInt64: fn = detail::PickInt<uint64_t>(op); break;
    case kFloat32: fn = detail::PickArith<float>(op); break;   // no BitOR
    case kFloat64: fn = detail::PickArith<double>(op); break;  // no BitOR
    default: Fail(StrFormat("unknown dtype enum %d", dtype));
  }
  if (fn == nullptr) {
    Fail(StrFormat("op %d not supported for dtype %d "
                   "(BitOR on float rejected)", op, dtype));
  }
  return fn;
}

}  // namespace rt

#endif  // RT_REDUCER_H_
