// Minimal OpenMPI-4.x ABI declarations for the exact call subset
// MpiComm (engine_mpi.h) uses — a stand-in for <mpi.h> on images that
// ship the OpenMPI RUNTIME (libmpi.so.40, present here via
// libopenmpi3) but not the development headers. The reference proves
// its MPI engine by building against a real MPI (engine_mpi.cc,
// test/Makefile:60-62); this shim lets us do the same against the
// system's real libmpi without the missing mpi.h.
//
// ABI notes (OpenMPI 4.1, verified against libmpi.so.40's dynamic
// symbol table and exercised by native/test/mpi_engine_test.cc):
//  - handles are pointers to opaque ompi_* structs;
//  - predefined handles are ADDRESSES of exported globals
//    (ompi_mpi_comm_world, ompi_mpi_byte, ompi_mpi_op_sum, ...);
//  - MPI_IN_PLACE is the sentinel pointer (void*)1.
// If a real <mpi.h> is available, prefer it: -DRT_MPI_REAL_HEADER.
#ifndef RT_MPI_ABI_SHIM_H_
#define RT_MPI_ABI_SHIM_H_

#ifdef RT_MPI_REAL_HEADER
#include <mpi.h>
#else

extern "C" {

typedef struct ompi_communicator_t* MPI_Comm;
typedef struct ompi_datatype_t* MPI_Datatype;
typedef struct ompi_op_t* MPI_Op;

typedef void (MPI_User_function)(void* in, void* inout, int* len,
                                 MPI_Datatype* dtype);

// predefined handles: addresses of exported globals (OpenMPI mpi.h
// does exactly this through OMPI_PREDEFINED_GLOBAL)
extern struct ompi_predefined_communicator_t ompi_mpi_comm_world
    __asm__("ompi_mpi_comm_world");
extern struct ompi_predefined_datatype_t ompi_mpi_byte
    __asm__("ompi_mpi_byte");
#define MPI_COMM_WORLD ((MPI_Comm)(void*)&ompi_mpi_comm_world)
#define MPI_BYTE ((MPI_Datatype)(void*)&ompi_mpi_byte)
#define MPI_IN_PLACE ((void*)1)
#define MPI_SUCCESS 0

int MPI_Init(int* argc, char*** argv);
int MPI_Initialized(int* flag);
int MPI_Finalize(void);
int MPI_Finalized(int* flag);
int MPI_Comm_rank(MPI_Comm comm, int* rank);
int MPI_Comm_size(MPI_Comm comm, int* size);
int MPI_Type_contiguous(int count, MPI_Datatype oldtype,
                        MPI_Datatype* newtype);
int MPI_Type_commit(MPI_Datatype* dtype);
int MPI_Type_free(MPI_Datatype* dtype);
int MPI_Op_create(MPI_User_function* fn, int commute, MPI_Op* op);
int MPI_Op_free(MPI_Op* op);
int MPI_Allreduce(const void* sendbuf, void* recvbuf, int count,
                  MPI_Datatype dtype, MPI_Op op, MPI_Comm comm);
int MPI_Bcast(void* buf, int count, MPI_Datatype dtype, int root,
              MPI_Comm comm);

}  // extern "C"

#endif  // RT_MPI_REAL_HEADER
#endif  // RT_MPI_ABI_SHIM_H_
