// MpiComm — alternative collective engine on MPI, compiled only when
// CMake finds an MPI toolchain (-DRT_WITH_MPI). Capability parity with
// the reference's engine_mpi.cc: full collective API on MPI_COMM_WORLD,
// custom reducers via MPI_Op_create over a contiguous byte datatype
// (engine_mpi.cc:124-237), checkpoint APIs version-only no-ops —
// explicitly NOT fault tolerant (engine_mpi.cc:47-60). Its role, like
// the reference's, is an independent second implementation of the same
// semantics for cross-checking and speed comparison (test/Makefile:60-62
// builds speed_test against both engines).
//
// NOTE: the build image for this repo has no MPI; this engine is
// compile-gated and exercised only where an MPI toolchain exists.
#ifndef RT_ENGINE_MPI_H_
#define RT_ENGINE_MPI_H_

#ifdef RT_WITH_MPI

#include <mpi.h>

#include <cstdio>
#include <string>

#include "comm.h"

namespace rt {

namespace mpi_detail {
// The engine is documented single-threaded (like the reference API,
// rabit.h:177-178), so the in-flight reduction context can be file-scope.
struct ReduceCtx {
  ReduceFn fn = nullptr;
};
inline ReduceCtx& Ctx() {
  static ReduceCtx c;
  return c;
}
inline void Trampoline(void* invec, void* inoutvec, int* len,
                       MPI_Datatype*) {
  // MPI semantics: inout[i] = in[i] op inout[i]; our ReduceFn folds src
  // into dst, which is the same elementwise combine for commutative ops
  Ctx().fn(inoutvec, invec, static_cast<size_t>(*len));
}
}  // namespace mpi_detail

class MpiComm : public Comm {
 public:
  void Init(int argc, const char* const* argv) override {
    cfg_.LoadEnv();
    cfg_.LoadArgs(argc, argv);
    cfg_.LoadHadoopEnv();  // last: explicit env/argv settings win
    SetupFromConfig(cfg_);
    int flag = 0;
    MPI_Initialized(&flag);
    if (!flag) MPI_Init(nullptr, nullptr);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank_);
    MPI_Comm_size(MPI_COMM_WORLD, &world_);
  }

  void Shutdown() override {
    int flag = 0;
    MPI_Finalized(&flag);
    if (!flag) MPI_Finalize();
  }

  bool is_distributed() const override { return world_ > 1; }

  void Allreduce(void* buf, size_t elem_size, size_t count, ReduceFn reducer,
                 PrepareFn prepare = nullptr, void* prepare_arg = nullptr,
                 const char* = "", int = -1, int = -1) override {
    if (prepare) prepare(prepare_arg);
    if (world_ == 1 || count == 0) return;
    MPI_Datatype dtype;
    MPI_Type_contiguous(static_cast<int>(elem_size), MPI_BYTE, &dtype);
    MPI_Type_commit(&dtype);
    MPI_Op op;
    mpi_detail::Ctx().fn = reducer;
    MPI_Op_create(mpi_detail::Trampoline, /*commute=*/1, &op);
    MPI_Allreduce(MPI_IN_PLACE, buf, static_cast<int>(count), dtype, op,
                  MPI_COMM_WORLD);
    MPI_Op_free(&op);
    MPI_Type_free(&dtype);
  }

  void Broadcast(void* buf, size_t size, int root, const char* = "")
      override {
    if (world_ == 1 || size == 0) return;
    MPI_Bcast(buf, static_cast<int>(size), MPI_BYTE, root, MPI_COMM_WORLD);
  }

  void TrackerPrint(const std::string& msg) override {
    if (rank_ == 0) {
      fprintf(stdout, "%s\n", msg.c_str());
      fflush(stdout);
    }
  }
  // LoadCheckpoint/Checkpoint/LazyCheckpoint: inherited version-only
  // no-ops from Comm — matching the reference MPI engine's explicit
  // non-fault-tolerance (engine_mpi.cc:47-60).
};

}  // namespace rt

#endif  // RT_WITH_MPI
#endif  // RT_ENGINE_MPI_H_
