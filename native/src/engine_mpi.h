// MpiComm — alternative collective engine on MPI, compiled only when
// CMake finds an MPI toolchain (-DRT_WITH_MPI). Capability parity with
// the reference's engine_mpi.cc: full collective API on MPI_COMM_WORLD,
// custom reducers via MPI_Op_create over a contiguous byte datatype
// (engine_mpi.cc:124-237), checkpoint APIs version-only no-ops —
// explicitly NOT fault tolerant (engine_mpi.cc:47-60). Its role, like
// the reference's, is an independent second implementation of the same
// semantics for cross-checking and speed comparison (test/Makefile:60-62
// builds speed_test against both engines).
//
// Built when an MPI runtime is available: either a full toolchain
// (-DRT_MPI_REAL_HEADER with <mpi.h>) or the header-less OpenMPI
// runtime this image ships, declared through mpi_abi_shim.h.
#ifndef RT_ENGINE_MPI_H_
#define RT_ENGINE_MPI_H_

#ifdef RT_WITH_MPI

#include "mpi_abi_shim.h"

#include <cstdio>
#include <string>

#include "comm.h"

namespace rt {

namespace mpi_detail {
// The engine is documented single-threaded (like the reference API,
// rabit.h:177-178), so the in-flight reduction context can be file-scope.
struct ReduceCtx {
  ReduceFn fn = nullptr;
};
inline ReduceCtx& Ctx() {
  static ReduceCtx c;
  return c;
}
inline void Trampoline(void* invec, void* inoutvec, int* len,
                       MPI_Datatype*) {
  // MPI semantics: inout[i] = in[i] op inout[i]; our ReduceFn folds src
  // into dst, which is the same elementwise combine for commutative ops
  Ctx().fn(inoutvec, invec, static_cast<size_t>(*len));
}
}  // namespace mpi_detail

class MpiComm : public Comm {
 public:
  void Init(int argc, const char* const* argv) override {
    cfg_.LoadEnv();
    cfg_.LoadArgs(argc, argv);
    cfg_.LoadHadoopEnv();  // last: explicit env/argv settings win
    SetupFromConfig(cfg_);
    int finalized = 0;
    MPI_Finalized(&finalized);
    if (finalized) {
      // MPI cannot be re-initialized after MPI_Finalize; fail loudly
      // instead of calling MPI_Comm_rank on finalized MPI (which
      // aborts the process, bypassing the error-return ABI)
      Fail("MPI was already finalized in this process; the MPI engine "
           "cannot be re-initialized (MPI_Init-once semantics)");
    }
    int inited = 0;
    MPI_Initialized(&inited);
    if (!inited) {
      MPI_Init(nullptr, nullptr);
      we_initialized_ = true;
    }
    MPI_Comm_rank(MPI_COMM_WORLD, &rank_);
    MPI_Comm_size(MPI_COMM_WORLD, &world_);
  }

  void Shutdown() override {
    FreeCachedOp();
    int flag = 0;
    MPI_Finalized(&flag);
    // only finalize an MPI this engine initialized: the host program
    // (e.g. mpi4py) may own the MPI lifecycle
    if (!flag && we_initialized_) MPI_Finalize();
  }

  bool is_distributed() const override { return world_ > 1; }

  void Allreduce(void* buf, size_t elem_size, size_t count, ReduceFn reducer,
                 PrepareFn prepare = nullptr, void* prepare_arg = nullptr,
                 const char* = "", int = -1, int = -1) override {
    if (prepare) prepare(prepare_arg);
    if (world_ == 1 || count == 0) return;
    // cache the committed datatype (keyed by elem_size) and the op
    // across calls — per-call create/commit/free would bias the speed
    // comparison this engine exists for (the reference's ReduceHandle
    // reuses both, engine_mpi.cc:189-237)
    if (cached_elem_size_ != elem_size) {
      if (cached_elem_size_ != 0) MPI_Type_free(&cached_dtype_);
      MPI_Type_contiguous(static_cast<int>(elem_size), MPI_BYTE,
                          &cached_dtype_);
      MPI_Type_commit(&cached_dtype_);
      cached_elem_size_ = elem_size;
    }
    if (!op_created_) {
      MPI_Op_create(mpi_detail::Trampoline, /*commute=*/1, &cached_op_);
      op_created_ = true;
    }
    mpi_detail::Ctx().fn = reducer;  // trampoline dispatches per call
    MPI_Allreduce(MPI_IN_PLACE, buf, static_cast<int>(count),
                  cached_dtype_, cached_op_, MPI_COMM_WORLD);
  }

  void Broadcast(void* buf, size_t size, int root, const char* = "")
      override {
    if (world_ == 1 || size == 0) return;
    MPI_Bcast(buf, static_cast<int>(size), MPI_BYTE, root, MPI_COMM_WORLD);
  }

  void TrackerPrint(const std::string& msg) override {
    if (rank_ == 0) {
      fprintf(stdout, "%s\n", msg.c_str());
      fflush(stdout);
    }
  }
  // LoadCheckpoint/Checkpoint/LazyCheckpoint: inherited version-only
  // no-ops from Comm — matching the reference MPI engine's explicit
  // non-fault-tolerance (engine_mpi.cc:47-60).

 private:
  void FreeCachedOp() {
    int finalized = 0;
    MPI_Finalized(&finalized);
    if (finalized) return;  // handles die with MPI
    if (op_created_) {
      MPI_Op_free(&cached_op_);
      op_created_ = false;
    }
    if (cached_elem_size_ != 0) {
      MPI_Type_free(&cached_dtype_);
      cached_elem_size_ = 0;
    }
  }

  bool we_initialized_ = false;
  bool op_created_ = false;
  size_t cached_elem_size_ = 0;
  MPI_Datatype cached_dtype_{};
  MPI_Op cached_op_{};
};

}  // namespace rt

#endif  // RT_WITH_MPI
#endif  // RT_ENGINE_MPI_H_
