#include "net.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <stddef.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <atomic>
#include <random>
#include <thread>

#include "log.h"

namespace rt {

// CRC-32, IEEE/zlib polynomial (0xEDB88320 reflected), table-driven.
// Table built once, thread-safe via C++11 static-init guarantees.
static const uint32_t* Crc32Table() {
  static uint32_t table[256];
  static const bool init = [] {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    return true;
  }();
  (void)init;
  return table;
}

uint32_t Crc32(const void* data, size_t n) {
  return Crc32Feed(Crc32Begin(), data, n) ^ 0xFFFFFFFFu;
}

uint32_t Crc32Begin() { return 0xFFFFFFFFu; }

uint32_t Crc32Feed(uint32_t state, const void* data, size_t n) {
  const uint32_t* table = Crc32Table();
  const unsigned char* p = static_cast<const unsigned char*>(data);
  for (size_t i = 0; i < n; ++i)
    state = table[(state ^ p[i]) & 0xFFu] ^ (state >> 8);
  return state;
}

uint32_t Crc32End(uint32_t state) { return state ^ 0xFFFFFFFFu; }

// Interrupt flag is process-global: the watchdog's monitor thread has
// no engine handle, and the engine's thread-local comm slot would hide
// a flag set from another thread anyway. The flag is a bare atomic
// (checked per poll iteration — no lock on the hot path); the reason
// string cannot be atomic, so it gets its own mutex. Reason is written
// BEFORE the flag is raised, so a consumer that saw the flag reads a
// reason at least as new as the request it consumed.
static std::atomic<bool> g_interrupt{false};
static Mutex g_interrupt_mu;
static std::string g_interrupt_reason RT_GUARDED_BY(g_interrupt_mu);

void RequestInterrupt(const std::string& reason) {
  {
    LockGuard hold(g_interrupt_mu);
    g_interrupt_reason = reason;
  }
  g_interrupt.store(true);
}

bool TakeInterrupt() { return g_interrupt.exchange(false); }

std::string LastInterruptReason() {
  LockGuard hold(g_interrupt_mu);
  return g_interrupt_reason;
}

TcpConn& TcpConn::operator=(TcpConn&& o) noexcept {
  if (this != &o) {
    Close();
    fd_ = o.fd_;
    o.fd_ = -1;
  }
  return *this;
}

void TcpConn::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

static sockaddr_in ResolveV4(const std::string& host, int port) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    int rc = getaddrinfo(host.c_str(), nullptr, &hints, &res);
    RT_CHECK(rc == 0 && res != nullptr,
             StrFormat("cannot resolve host %s", host.c_str()));
    addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    freeaddrinfo(res);
  }
  return addr;
}

std::string TcpConn::ResolveHost(const std::string& host) {
  sockaddr_in addr = ResolveV4(host, 0);
  char buf[INET_ADDRSTRLEN] = {0};
  inet_ntop(AF_INET, &addr.sin_addr, buf, sizeof(buf));
  return std::string(buf);
}

TcpConn TcpConn::Connect(const std::string& host, int port, int retries,
                         int delay_ms) {
  sockaddr_in addr = ResolveV4(host, port);
  for (int attempt = 0;; ++attempt) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    RT_CHECK(fd >= 0, "socket() failed");
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      TcpConn c(fd);
      c.SetNoDelay();
      return c;
    }
    ::close(fd);
    if (attempt >= retries) {
      Fail(StrFormat("connect %s:%d failed after %d attempts: %s",
                     host.c_str(), port, attempt + 1, strerror(errno)));
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(delay_ms));
  }
}

void TcpConn::SetNonBlocking(bool on) {
  int flags = fcntl(fd_, F_GETFL, 0);
  if (on) flags |= O_NONBLOCK; else flags &= ~O_NONBLOCK;
  RT_CHECK(fcntl(fd_, F_SETFL, flags) == 0, "fcntl failed");
}

void TcpConn::SetNoDelay() {
  int one = 1;
  setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

void TcpConn::SetKeepAlive() {
  int one = 1;
  setsockopt(fd_, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
}

bool TcpConn::RecvAllTimeout(void* data, size_t n, int timeout_ms) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    pollfd pfd{fd_, POLLIN, 0};
    int rc = ::poll(&pfd, 1, timeout_ms);
    if (rc < 0 && errno == EINTR) continue;
    if (rc <= 0) return false;  // timeout or poll error
    ssize_t k = ::recv(fd_, p, n, 0);
    if (k < 0) {
      if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK) continue;
      return false;
    }
    if (k == 0) return false;  // peer closed mid-handshake
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

void TcpConn::SendAll(const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t k = ::send(fd_, p, n, MSG_NOSIGNAL);
    if (k < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        pollfd pfd{fd_, POLLOUT, 0};
        ::poll(&pfd, 1, -1);
        continue;
      }
      Fail(StrFormat("send failed: %s", strerror(errno)));
    }
    p += k;
    n -= static_cast<size_t>(k);
  }
}

void TcpConn::RecvAll(void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t k = ::recv(fd_, p, n, 0);
    if (k < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        pollfd pfd{fd_, POLLIN, 0};
        ::poll(&pfd, 1, -1);
        continue;
      }
      Fail(StrFormat("recv failed: %s", strerror(errno)));
    }
    RT_CHECK(k != 0, "connection closed by peer during RecvAll");
    p += k;
    n -= static_cast<size_t>(k);
  }
}

void TcpConn::SendU32(uint32_t v) { SendAll(&v, sizeof(v)); }
uint32_t TcpConn::RecvU32() {
  uint32_t v = 0;
  RecvAll(&v, sizeof(v));
  return v;
}

void TcpConn::SendStr(const std::string& s) {
  SendU32(static_cast<uint32_t>(s.size()));
  SendAll(s.data(), s.size());
}

std::string TcpConn::RecvStr() {
  uint32_t n = RecvU32();
  std::string s(n, '\0');
  if (n) RecvAll(&s[0], n);
  return s;
}

ssize_t TcpConn::TrySend(const void* data, size_t n, NetResult* res) {
  ssize_t k = ::send(fd_, data, n, MSG_NOSIGNAL);
  if (k >= 0) {
    *res = NetResult::kOk;
    return k;
  }
  if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) {
    *res = NetResult::kAgain;
    return 0;
  }
  *res = (errno == ECONNRESET || errno == EPIPE) ? NetResult::kReset
                                                 : NetResult::kError;
  return -1;
}

ssize_t TcpConn::TryRecv(void* data, size_t n, NetResult* res) {
  ssize_t k = ::recv(fd_, data, n, 0);
  if (k > 0) {
    *res = NetResult::kOk;
    return k;
  }
  if (k == 0) {  // orderly shutdown == peer death (reference
                 // allreduce_base.h:320-323)
    *res = NetResult::kReset;
    return -1;
  }
  if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) {
    *res = NetResult::kAgain;
    return 0;
  }
  *res = (errno == ECONNRESET) ? NetResult::kReset : NetResult::kError;
  return -1;
}

// abstract-namespace address for a listener token: sun_path[0] == '\0',
// name carries no filesystem state
static socklen_t LocalAddr(const std::string& token, sockaddr_un* addr) {
  memset(addr, 0, sizeof(*addr));
  addr->sun_family = AF_UNIX;
  int n = snprintf(addr->sun_path + 1, sizeof(addr->sun_path) - 1,
                   "rabit_tpu.%s", token.c_str());
  return static_cast<socklen_t>(offsetof(sockaddr_un, sun_path) + 1 + n);
}

// 64 random bits, hex. Identity of the UDS twin: peers learn it only
// through the tracker, so resolving it proves same host + same netns —
// unlike a port-derived name, which any co-located world (or a worker
// on another host behind the same SNAT, which fools source-IP
// single-host inference) could coincidentally own.
static std::string RandomToken() {
  std::random_device rd;
  uint64_t v = (static_cast<uint64_t>(rd()) << 32) ^ rd() ^
               (static_cast<uint64_t>(::getpid()) << 17);
  char buf[17];
  snprintf(buf, sizeof(buf), "%016llx",
           static_cast<unsigned long long>(v));
  return std::string(buf);
}

TcpConn TcpConn::ConnectLocal(const std::string& token) {
  if (token.empty()) return TcpConn();
  sockaddr_un addr;
  socklen_t len = LocalAddr(token, &addr);
  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return TcpConn();
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), len) != 0) {
    ::close(fd);
    return TcpConn();  // caller falls back to TCP
  }
  return TcpConn(fd);
}

void Listener::Bind(int port_start, int ntrial, bool with_local) {
  fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  RT_CHECK(fd_ >= 0, "socket() failed");
  int one = 1;
  setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  for (int p = port_start; p < port_start + ntrial; ++p) {
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(p));
    if (::bind(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      RT_CHECK(::listen(fd_, 256) == 0, "listen failed");
      if (p == 0) {  // ephemeral: ask the kernel which port it picked
        sockaddr_in got{};
        socklen_t len = sizeof(got);
        RT_CHECK(getsockname(fd_, reinterpret_cast<sockaddr*>(&got),
                             &len) == 0, "getsockname failed");
        port_ = ntohs(got.sin_port);
      } else {
        port_ = p;
      }
      // same-host fast-path twin under a random token the tracker
      // relays to peers; best-effort — a failed bind (exotic netns
      // restrictions) just leaves TCP-only service
      if (!with_local) return;
      ufd_ = ::socket(AF_UNIX, SOCK_STREAM, 0);
      if (ufd_ >= 0) {
        // port prefix for human observability (ss -x / /proc/net/unix
        // group by world); the random suffix is the actual identity —
        // connecting requires the full tracker-relayed name
        token_ = StrFormat("%d.%s", port_, RandomToken().c_str());
        sockaddr_un uaddr;
        socklen_t ulen = LocalAddr(token_, &uaddr);
        if (::bind(ufd_, reinterpret_cast<sockaddr*>(&uaddr), ulen) != 0 ||
            ::listen(ufd_, 256) != 0) {
          ::close(ufd_);
          ufd_ = -1;
          token_.clear();
        }
      }
      return;
    }
  }
  Fail(StrFormat("no free port in [%d, %d)", port_start, port_start + ntrial));
}

TcpConn Listener::Accept() {
  for (;;) {
    int fd;
    if (ufd_ < 0) {
      fd = ::accept(fd_, nullptr, nullptr);
    } else {
      pollfd pfds[2] = {{fd_, POLLIN, 0}, {ufd_, POLLIN, 0}};
      int rc = ::poll(pfds, 2, -1);
      if (rc < 0) {
        if (errno == EINTR) continue;
        Fail(StrFormat("accept poll failed: %s", strerror(errno)));
      }
      // UDS first: when both raced to readiness, prefer the fast path
      fd = ::accept(pfds[1].revents & POLLIN ? ufd_ : fd_, nullptr, nullptr);
    }
    if (fd >= 0) {
      TcpConn c(fd);
      c.SetNoDelay();  // no-op on AF_UNIX (setsockopt result ignored)
      return c;
    }
    if (errno == EINTR || errno == ECONNABORTED) continue;
    Fail(StrFormat("accept failed: %s", strerror(errno)));
  }
}

TcpConn Listener::AcceptTimeout(int timeout_ms) {
  for (;;) {
    pollfd pfds[2] = {{fd_, POLLIN, 0}, {ufd_, POLLIN, 0}};
    int npfd = ufd_ < 0 ? 1 : 2;
    int rc = ::poll(pfds, npfd, timeout_ms);
    if (rc < 0) {
      if (errno == EINTR) continue;
      return TcpConn();
    }
    if (rc == 0) return TcpConn();  // timeout: caller escalates
    // UDS first, mirroring Accept(): prefer the fast path on a race
    int lfd = (npfd == 2 && (pfds[1].revents & POLLIN)) ? ufd_ : fd_;
    int fd = ::accept(lfd, nullptr, nullptr);
    if (fd >= 0) {
      TcpConn c(fd);
      c.SetNoDelay();
      return c;
    }
    if (errno == EINTR || errno == ECONNABORTED) continue;
    return TcpConn();
  }
}

void Listener::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
  if (ufd_ >= 0) {
    ::close(ufd_);
    ufd_ = -1;
    token_.clear();
  }
}

void Poller::WatchRead(int fd) { fds_.push_back({fd, POLLIN, 0}); }
void Poller::WatchWrite(int fd) { fds_.push_back({fd, POLLOUT, 0}); }

int Poller::Wait(int timeout_ms) {
  for (;;) {
    int rc = ::poll(fds_.data(), fds_.size(), timeout_ms);
    if (rc >= 0 || errno != EINTR) return rc;
  }
}

bool Poller::CanRead(int fd) const {
  for (const auto& p : fds_)
    if (p.fd == fd && (p.revents & (POLLIN | POLLHUP | POLLERR))) return true;
  return false;
}

bool Poller::CanWrite(int fd) const {
  for (const auto& p : fds_)
    if (p.fd == fd && (p.revents & (POLLOUT | POLLHUP | POLLERR))) return true;
  return false;
}

std::string GetHostName() {
  char buf[256];
  if (gethostname(buf, sizeof(buf)) != 0) return "localhost";
  buf[sizeof(buf) - 1] = '\0';
  return std::string(buf);
}

}  // namespace rt
