#include "robust.h"

#include <algorithm>
#include <cstring>

namespace rt {

// ---------------------------------------------------------------------------
// small helpers
// ---------------------------------------------------------------------------

static void ReduceAction(void* d, const void* s, size_t n) {
  auto* dst = static_cast<RobustComm::ActionPod*>(d);
  auto* src = static_cast<const RobustComm::ActionPod*>(s);
  for (size_t i = 0; i < n; ++i) {
    dst[i].flags |= src[i].flags;
    if (src[i].seqno < dst[i].seqno) dst[i].seqno = src[i].seqno;
    if (src[i].neg_seqno < dst[i].neg_seqno)
      dst[i].neg_seqno = src[i].neg_seqno;
  }
}

static void ReduceMaxU64(void* d, const void* s, size_t n) {
  auto* dst = static_cast<uint64_t*>(d);
  auto* src = static_cast<const uint64_t*>(s);
  for (size_t i = 0; i < n; ++i)
    if (src[i] > dst[i]) dst[i] = src[i];
}

// byte-wise OR — position-independent, so safe for any fold offset the
// streaming tree produces (unlike a layout-aware struct reducer)
static void ReduceOrBytes(void* d, const void* s, size_t n) {
  auto* dst = static_cast<uint8_t*>(d);
  auto* src = static_cast<const uint8_t*>(s);
  for (size_t i = 0; i < n; ++i) dst[i] |= src[i];
}

static const uint32_t kRankBits = 20;  // world_size < 2^20
static const uint32_t kRankMask = (1u << kRankBits) - 1;

void RobustComm::Init(int argc, const char* const* argv) {
  Comm::Init(argc, argv);
  bootstrap_cache_enabled_ = cfg_.GetBool("rabit_bootstrap_cache", false);
  num_local_replica_ =
      static_cast<int>(cfg_.GetInt("rabit_local_replica", 2));
  num_global_replica_ =
      static_cast<int>(cfg_.GetInt("rabit_global_replica", 5));
  result_round_ = (num_global_replica_ > 0)
      ? static_cast<uint32_t>(std::max(1, world_ / num_global_replica_))
      : 1;  // <=0: keep every result on every rank
  collective_retries_ = static_cast<int>(
      cfg_.GetInt("rabit_collective_retries", 1000));
  if (collective_retries_ < 1) collective_retries_ = 1;
}

void RobustComm::Resize(const char* cmd) {
  // Elastic shrink/grow without process exit: the base rewire
  // reassigns rank_/world_/world_epoch_ from the fresh tracker
  // assignment; everything below is recovery state whose meaning is
  // WORLD-SIZED and therefore dead the moment the world changes —
  // result-log ownership rotates modulo result_round_ (a function of
  // world_), replayed seqnos pair ranks that may no longer exist, and
  // replica_local_ slots mirror ring predecessors of the OLD ring.
  // The global checkpoint and version counter survive untouched: they
  // are world-shape-independent and version continuity across a
  // resize is the whole point of resizing in-process.
  Comm::Resize(cmd);
  result_round_ = (num_global_replica_ > 0)
      ? static_cast<uint32_t>(std::max(1, world_ / num_global_replica_))
      : 1;
  result_log_.clear();
  seq_counter_ = 0;
  bootstrap_cache_.clear();
  for (auto& s : replica_local_) s.clear();
}

void RobustComm::InitAfterException() {
  if (!is_distributed()) return;  // single-node: nothing to reset
  CheckAndRecover(NetResult::kReset);
}

void RobustComm::Shutdown() {
  // Two-phase consensus exit (reference allreduce_robust.cc:54-67): a
  // rank that finished its last iteration must NOT drop links while a
  // respawned straggler still needs its result log or checkpoint.
  // Phase 1 is a pseudo-checkpoint fence: loop in consensus rounds —
  // serving checkpoint loads (kLoadCheck) and seq replays (diff-seq)
  // for laggards — until the whole world holds the fence flag at the
  // same seq. Only then is it safe to drop the recovery state. Phase 2
  // (ack) keeps links up until everyone has passed phase 1, so no rank
  // can observe a half-shut-down world and misread it as a failure.
  if (is_distributed() && world_ > 1) {
    RecoverExec(nullptr, 0, kCheckPoint, seq_counter_);
    result_log_.clear();
    seq_counter_ = 0;
    bootstrap_cache_.clear();
    RecoverExec(nullptr, 0, kCheckAck, seq_counter_);
  }
  Comm::Shutdown();
}

// elect word helpers for packed plan phases
static inline uint64_t ElectWord(bool have, uint64_t key, int rank) {
  return have ? ((key << kRankBits) |
                 (kRankMask - static_cast<uint32_t>(rank)))
              : 0;
}
static inline int ElectedRank(uint64_t word) {
  return static_cast<int>(kRankMask - (word & kRankMask));
}

// non-retrying elect of max (key, world-rank): every rank contributes
// key<<20 | (mask - rank); errors unwind to RecoverExec
NetResult RobustComm::TryElect(uint64_t key, uint64_t* out_key,
                               int* out_rank) {
  uint64_t word = ElectWord(true, key, rank_);
  NetResult res = TryAllreduce(&word, sizeof(word), 1, ReduceMaxU64);
  if (res != NetResult::kOk) return res;
  *out_key = word >> kRankBits;
  *out_rank = ElectedRank(word);
  return res;
}

// need-bitmask OR'd across ranks in one consensus round; fills the
// per-rank need vector every rank agrees on
NetResult RobustComm::AgreeNeed(bool mine, std::vector<uint8_t>* need,
                                std::vector<uint8_t>* mask_scratch) {
  std::vector<uint8_t>& mask = *mask_scratch;
  mask.assign((world_ + 7) / 8, 0);
  if (mine) mask[rank_ / 8] = static_cast<uint8_t>(1u << (rank_ % 8));
  NetResult res = TryAllreduce(mask.data(), 1, mask.size(), ReduceOrBytes);
  if (res != NetResult::kOk) return res;
  need->assign(world_, 0);
  for (int r = 0; r < world_; ++r)
    (*need)[r] = (mask[r / 8] >> (r % 8)) & 1;
  return res;
}

void RobustComm::ConsensusAllreduce(void* buf, size_t elem_size, size_t count,
                                    ReduceFn fn) {
  std::string pristine(static_cast<char*>(buf), elem_size * count);
  for (int attempt = 0; attempt < collective_retries_; ++attempt) {
    NetResult res = TryAllreduce(buf, elem_size, count, fn);
    if (res == NetResult::kOk) return;
    memcpy(buf, pristine.data(), pristine.size());
    CheckAndRecover(res);
  }
  Fail(StrFormat("consensus allreduce failed after %d recovery attempts",
                 collective_retries_));
}

// Every in-collective recovery — link reset, frame-retry exhaustion, or
// an out-of-band interrupt (NetResult::kInterrupt from the watchdog's
// reform rung) — converges here: peers blocked in Try* observe the conn
// teardown as kReset and realign in the same global re-formation.
void RobustComm::CheckAndRecover(NetResult res) {
  ++recover_counter_;
  ++stat_retries_;  // provenance counter, drained by the Python engine
  if (res == NetResult::kInterrupt) {
    // attribute the reset: the raiser tagged the request with its
    // provenance (e.g. "watchdog_reform"), sticky in the net layer
    LogInfo(StrFormat("rank %d recovery #%d from interrupt (%s)", rank_,
                      recover_counter_, LastInterruptReason().c_str()));
  } else if (debug_) {
    LogInfo(StrFormat("rank %d entering recovery #%d", rank_,
                      recover_counter_));
  }
  ReconnectLinks("recover");
}

// ---------------------------------------------------------------------------
// consensus rounds
// ---------------------------------------------------------------------------

bool RobustComm::RecoverExec(void* buf, size_t size, uint32_t flag,
                             uint32_t my_seq, const std::string& cache_key) {
  for (;;) {
    // heartbeat per consensus round (reference calls ReportStatus each
    // RecoverExec round, allreduce_robust.cc:1062) so a streaming
    // scheduler sees long recoveries as alive, not hung
    ReportStatus("recover", my_seq);
    ActionPod act;
    act.flags = flag;
    act.seqno = my_seq;
    act.neg_seqno = ~my_seq;
    ConsensusAllreduce(&act, sizeof(act), 1, ReduceAction);
    uint32_t min_seq = act.seqno;
    uint32_t max_seq = ~act.neg_seqno;
    if (debug_) {
      LogInfo(StrFormat("rank %d round: flags=%x min=%u max=%u "
                        "(mine: flag=%x seq=%u ver=%d)",
                        rank_, act.flags, min_seq, max_seq, flag, my_seq,
                        version_));
    }

    if (act.flags & kLoadCheck) {
      NetResult res = TryServeLoadCheckpoint();
      if (res != NetResult::kOk) {
        CheckAndRecover(res);
        continue;
      }
      if (flag & kLoadCheck) return true;
      continue;
    }
    if (act.flags & kLoadBootstrap) {
      bool mine = (flag & kLoadBootstrap) != 0;
      // Only ONE requester is elected and filled per round; an unelected
      // requester must loop into the next round, or it would return with
      // an untouched buffer and cache garbage.
      bool served = false;
      NetResult res = TryServeBootstrap(buf, size, mine, cache_key, &served);
      if (res != NetResult::kOk) {
        CheckAndRecover(res);
        continue;
      }
      if (served) return true;
      continue;
    }
    if (min_seq != max_seq) {
      // someone lags: replay op min_seq from a holder to its requesters
      bool i_am_requester = (my_seq == min_seq) && (flag == 0);
      NetResult res = TryServeReplay(min_seq, buf, size, i_am_requester);
      if (res != NetResult::kOk) {
        CheckAndRecover(res);
        continue;
      }
      if (i_am_requester) return true;
      continue;
    }
    if (act.flags & kCheckPoint) {
      if (flag & kCheckPoint) return false;  // everyone at the same fence
      continue;
    }
    if (act.flags & kCheckAck) {
      if (flag & kCheckAck) return false;
      continue;
    }
    return false;  // uniform, nothing requested: execute the op fresh
  }
}

NetResult RobustComm::TryServeLoadCheckpoint() {
  // materialize a pending lazy checkpoint now that a failure needs it
  // (reference allreduce_robust.cc:957-964)
  if (lazy_global_ != nullptr) {
    global_ckpt_ = *lazy_global_;
    lazy_global_ = nullptr;
  }
  uint64_t max_version = 0;
  int vrank = 0;
  NetResult res = TryElect(static_cast<uint64_t>(version_), &max_version,
                           &vrank);
  if (res != NetResult::kOk) return res;
  if (max_version == 0) return NetResult::kOk;

  // One packed plan round: [g_need bits | l_need bits], byte-OR'd.
  // EVERY rank participates unconditionally: gating on local config
  // (e.g. num_local_replica_) would desync the protocol, because a
  // freshly restarted rank and the survivors disagree on it until this
  // round resolves the truth. Replaces the per-rank election loop
  // (2 consensus rounds x world) with O(1) rounds (VERDICT r2 #2).
  const bool g_need_mine = static_cast<uint64_t>(version_) < max_version;
  const bool l_need_mine = local_ckpt_.empty() && local_expected_;
  const size_t mb = (world_ + 7) / 8;
  std::vector<uint8_t> mask(2 * mb, 0);
  if (g_need_mine) mask[rank_ / 8] |= static_cast<uint8_t>(1u << (rank_ % 8));
  if (l_need_mine)
    mask[mb + rank_ / 8] |= static_cast<uint8_t>(1u << (rank_ % 8));
  res = TryAllreduce(mask.data(), 1, mask.size(), ReduceOrBytes);
  if (res != NetResult::kOk) return res;
  std::vector<uint8_t> g_need(world_, 0), l_need(world_, 0);
  bool any_g = false, any_l = false;
  for (int r = 0; r < world_; ++r) {
    g_need[r] = (mask[r / 8] >> (r % 8)) & 1;
    l_need[r] = (mask[mb + r / 8] >> (r % 8)) & 1;
    any_g = any_g || g_need[r];
    any_l = any_l || l_need[r];
  }

  // Global checkpoint: the version election above already produced a
  // max-version holder (vrank); agree its payload length (stale ranks
  // contribute 0 so they cannot win the MAX), then stream ONLY to the
  // lagging ranks along tree paths (reference routes with
  // MsgPassing/TryRecoverData, allreduce_robust.cc:925-976; full-world
  // broadcast was the r2 gap).
  if (any_g) {
    const bool have_g = static_cast<uint64_t>(version_) == max_version;
    const int holder = vrank;
    uint64_t len = have_g ? global_ckpt_.size() : 0;
    res = TryAllreduce(&len, sizeof(uint64_t), 1, ReduceMaxU64);
    if (res != NetResult::kOk) return res;
    if (len > 0) {
      std::string payload;
      char* data = nullptr;
      if (rank_ == holder) {
        RT_CHECK(global_ckpt_.size() == len,
                 "global checkpoint size disagrees with agreed plan");
        data = &global_ckpt_[0];
      } else if (g_need_mine) {
        payload.resize(len);
        data = &payload[0];
      }
      res = TryRouteData(data, len, holder, g_need);
      if (res != NetResult::kOk) return res;
      if (g_need_mine) global_ckpt_ = payload;
    } else if (g_need_mine) {
      global_ckpt_.clear();
    }
    if (g_need_mine) {
      version_ = static_cast<int>(max_version);
      seq_counter_ = 0;
      result_log_.clear();
    }
  }

  // Local-checkpoint healing (reference TryRecoverLocalState,
  // allreduce_robust.cc:1216-1347): one MAX round packs, for every rank
  // q, the elected holder of q's state and its length; then each needed
  // state streams only along the holder->q path.
  if (any_l) {
    std::vector<uint64_t> lplan(2 * world_, 0);
    for (int q = 0; q < world_; ++q) {
      int dist = (rank_ - q + world_) % world_;  // q stored at q+1..q+R
      std::string* slot = nullptr;
      if (q == rank_ && !local_ckpt_.empty()) {
        slot = &local_ckpt_;
      } else if (dist >= 1 && dist <= num_local_replica_ &&
                 static_cast<size_t>(dist - 1) < replica_local_.size() &&
                 !replica_local_[dist - 1].empty()) {
        slot = &replica_local_[dist - 1];
      }
      lplan[q] = ElectWord(slot != nullptr, 1, rank_);
      lplan[world_ + q] = slot ? slot->size() : 0;
    }
    res = TryAllreduce(lplan.data(), sizeof(uint64_t), lplan.size(),
                       ReduceMaxU64);
    if (res != NetResult::kOk) return res;
    for (int q = 0; q < world_; ++q) {
      if (!l_need[q] || lplan[q] == 0) continue;  // not needed / lost
      int src = ElectedRank(lplan[q]);
      uint64_t len = lplan[world_ + q];
      if (len == 0) {
        if (q == rank_) local_ckpt_.clear();
        continue;
      }
      std::vector<uint8_t> need_one(world_, 0);
      need_one[q] = 1;
      std::string payload;
      char* data = nullptr;
      if (rank_ == src) {
        int dist = (rank_ - q + world_) % world_;
        std::string* slot = (q == rank_) ? &local_ckpt_
                                         : &replica_local_[dist - 1];
        RT_CHECK(slot->size() == len,
                 "local replica size disagrees with agreed plan");
        data = &(*slot)[0];
      } else if (q == rank_) {
        payload.resize(len);
        data = &payload[0];
      }
      res = TryRouteData(data, len, src, need_one);
      if (res != NetResult::kOk) return res;
      if (q == rank_ && rank_ != src) local_ckpt_ = payload;
    }
  }
  return NetResult::kOk;
}

NetResult RobustComm::TryServeReplay(uint32_t seq, void* buf, size_t size,
                                     bool i_am_requester) {
  // plan: one MAX round elects the holder and carries the payload
  // length; one OR round agrees the requester set; then the payload
  // streams only along holder->requester tree paths (VERDICT r2 #2 —
  // the reference's targeted TryRecoverData capability,
  // allreduce_robust.cc:749-861 — replacing two full-world broadcasts)
  auto it = result_log_.find(seq);
  const bool have = it != result_log_.end();
  uint64_t plan[2] = {ElectWord(have, 1, rank_),
                      have ? it->second.size() : 0};
  NetResult res = TryAllreduce(plan, sizeof(uint64_t), 2, ReduceMaxU64);
  if (res != NetResult::kOk) return res;
  RT_CHECK(plan[0] != 0,
           StrFormat("replay of op %u requested but no rank has it "
                     "(all replica holders died)", seq));
  const int holder = ElectedRank(plan[0]);
  const uint64_t len = plan[1];
  std::vector<uint8_t> need, mask;
  res = AgreeNeed(i_am_requester, &need, &mask);
  if (res != NetResult::kOk) return res;
  if (i_am_requester) {
    RT_CHECK(len == size,
             StrFormat("replayed op %u size %llu != expected %zu", seq,
                       static_cast<unsigned long long>(len), size));
    return TryRouteData(static_cast<char*>(buf), len, holder, need);
  }
  if (rank_ == holder) {
    RT_CHECK(it->second.size() == len,
             "stored result size disagrees with agreed plan");
    return TryRouteData(&it->second[0], len, holder, need);
  }
  return TryRouteData(nullptr, len, holder, need);  // pass-through / idle
}

NetResult RobustComm::TryServeBootstrap(void* buf, size_t size, bool mine,
                                        const std::string& cache_key,
                                        bool* served) {
  // elect one requester per round; it broadcasts its cache key (every
  // rank needs the key to vote on holding it), then the elected holder
  // streams the cached value along the tree path to the requester only
  uint64_t rk = 0;
  int requester = 0;
  NetResult res = TryElect(mine ? 1 : 0, &rk, &requester);
  if (res != NetResult::kOk) return res;
  RT_CHECK(rk == 1, "bootstrap round without requester");
  bool lead = (rank_ == requester) && mine;
  uint64_t klen = lead ? cache_key.size() : 0;
  res = TryBroadcast(reinterpret_cast<char*>(&klen), sizeof(klen),
                     requester);
  if (res != NetResult::kOk) return res;
  std::string key(klen, '\0');
  if (lead) key = cache_key;
  if (klen > 0) {
    res = TryBroadcast(&key[0], klen, requester);
    if (res != NetResult::kOk) return res;
  }
  auto hit = bootstrap_cache_.find(key);
  const bool have = hit != bootstrap_cache_.end();
  uint64_t plan[2] = {ElectWord(have, 1, rank_),
                      have ? hit->second.size() : 0};
  res = TryAllreduce(plan, sizeof(uint64_t), 2, ReduceMaxU64);
  if (res != NetResult::kOk) return res;
  RT_CHECK(plan[0] != 0,
           "bootstrap cache miss cluster-wide for key: " + key);
  const int holder = ElectedRank(plan[0]);
  const uint64_t len = plan[1];
  std::vector<uint8_t> need(world_, 0);
  need[requester] = 1;
  char* data = nullptr;
  if (rank_ == holder) {
    RT_CHECK(hit->second.size() == len,
             "bootstrap cache size disagrees with agreed plan");
    data = &hit->second[0];
  } else if (lead) {
    RT_CHECK(len == size, "bootstrap replay size mismatch for " + key);
    data = static_cast<char*>(buf);
  }
  res = TryRouteData(data, len, holder, need);
  if (res != NetResult::kOk) return res;
  if (served) *served = lead;
  return NetResult::kOk;
}

// ---------------------------------------------------------------------------
// public collectives with recovery
// ---------------------------------------------------------------------------

void RobustComm::Allreduce(void* buf, size_t elem_size, size_t count,
                           ReduceFn reducer, PrepareFn prepare,
                           void* prepare_arg, const char* cache_key,
                           int dtype, int op) {
  OnEngineCall("allreduce");
  const size_t size = elem_size * count;
  if (world_ == 1) {
    if (prepare) prepare(prepare_arg);
    return;
  }
  std::string key = cache_key ? cache_key : "";
  // pre-LoadCheckpoint collectives go through the bootstrap cache and
  // consume NO sequence numbers (reference allreduce_robust.cc:174-180,
  // 212-218: results land in the signature-keyed cache instead of the
  // seq-indexed result buffer, so post-load numbering aligns across
  // fresh and restarted workers)
  const bool bootstrap_op =
      bootstrap_cache_enabled_ && before_first_load_ && !key.empty();
  if (bootstrap_op) {
    auto it = bootstrap_cache_.find(key);
    if (it != bootstrap_cache_.end()) {
      RT_CHECK(it->second.size() == size, "bootstrap cache size mismatch");
      memcpy(buf, it->second.data(), size);
      return;
    }
    if (num_attempt_ > 0) {
      // restarted before first load: fetch this op from a holder
      bool served = RecoverExec(buf, size, kLoadBootstrap, seq_counter_,
                                key);
      RT_CHECK(served, "bootstrap fetch round did not serve requester");
      FinishOp(buf, size, key, /*bootstrap=*/true);
      return;
    }
  }
  if (RecoverExec(buf, size, 0, seq_counter_, key)) {
    FinishOp(buf, size, key, bootstrap_op);
    return;
  }
  if (prepare) prepare(prepare_arg);
  double t0 = debug_ ? GetTime() : 0.0;
  std::string pristine(static_cast<char*>(buf), size);
  for (int attempt = 0;; ++attempt) {
    // bounded, not infinite: a persistent misconfiguration (e.g. a data
    // plane that can never form its device world) must fail loudly
    // instead of spinning through reconnect cycles forever
    RT_CHECK(attempt < collective_retries_,
             StrFormat("allreduce failed after %d recovery attempts — "
                       "persistent failure, not a transient death (check "
                       "data-plane/coordinator configuration)",
                       collective_retries_));
    // execute step: accelerator data plane when eligible, socket
    // tree/ring otherwise — the robust wrapper structure of the
    // reference (allreduce_robust.cc:159-219 around TryAllreduce)
    NetResult res = ExecuteAllreduce(buf, elem_size, count, reducer,
                                     dtype, op);
    if (res == NetResult::kOk) {
      // per-op latency trace (reference rabit_debug logging,
      // allreduce_robust.cc:206-210,262-268)
      if (debug_) {
        LogInfo(StrFormat("rank %d allreduce version=%d seq=%u bytes=%zu "
                          "key=%s %.6fs", rank_, version_, seq_counter_,
                          size, key.c_str(), GetTime() - t0));
      }
      FinishOp(buf, size, key, bootstrap_op);
      return;
    }
    CheckAndRecover(res);
    memcpy(buf, pristine.data(), size);
    if (RecoverExec(buf, size, 0, seq_counter_, key)) {
      FinishOp(buf, size, key, bootstrap_op);
      return;
    }
    memcpy(buf, pristine.data(), size);
  }
}

void RobustComm::Broadcast(void* buf, size_t size, int root,
                           const char* cache_key) {
  OnEngineCall("broadcast");
  if (world_ == 1) return;
  std::string key = cache_key ? cache_key : "";
  const bool bootstrap_op =
      bootstrap_cache_enabled_ && before_first_load_ && !key.empty();
  if (bootstrap_op) {
    auto it = bootstrap_cache_.find(key);
    if (it != bootstrap_cache_.end()) {
      RT_CHECK(it->second.size() == size, "bootstrap cache size mismatch");
      memcpy(buf, it->second.data(), size);
      return;
    }
    if (num_attempt_ > 0) {
      bool served = RecoverExec(buf, size, kLoadBootstrap, seq_counter_,
                                key);
      RT_CHECK(served, "bootstrap fetch round did not serve requester");
      FinishOp(buf, size, key, /*bootstrap=*/true);
      return;
    }
  }
  if (RecoverExec(buf, size, 0, seq_counter_, key)) {
    FinishOp(buf, size, key, bootstrap_op);
    return;
  }
  double t0 = debug_ ? GetTime() : 0.0;
  std::string pristine(static_cast<char*>(buf), size);
  for (int attempt = 0;; ++attempt) {
    RT_CHECK(attempt < collective_retries_,
             StrFormat("broadcast failed after %d recovery attempts — "
                       "persistent failure, not a transient death",
                       collective_retries_));
    NetResult res = TryBroadcast(static_cast<char*>(buf), size, root);
    if (res == NetResult::kOk) {
      if (debug_) {
        LogInfo(StrFormat("rank %d broadcast version=%d seq=%u bytes=%zu "
                          "key=%s %.6fs", rank_, version_, seq_counter_,
                          size, key.c_str(), GetTime() - t0));
      }
      FinishOp(buf, size, key, bootstrap_op);
      return;
    }
    CheckAndRecover(res);
    memcpy(buf, pristine.data(), size);
    if (RecoverExec(buf, size, 0, seq_counter_, key)) {
      FinishOp(buf, size, key, bootstrap_op);
      return;
    }
    memcpy(buf, pristine.data(), size);
  }
}

void RobustComm::FinishOp(const void* buf, size_t size,
                          const std::string& key, bool bootstrap) {
  if (bootstrap) {
    // pre-load ops: signature-keyed cache only, no seq consumption
    bootstrap_cache_[key] =
        std::string(static_cast<const char*>(buf), size);
    return;
  }
  // rotating ownership: only ~num_global_replica ranks keep each seqno
  if (result_round_ <= 1 ||
      seq_counter_ % result_round_ ==
          static_cast<uint32_t>(rank_) % result_round_) {
    result_log_[seq_counter_] =
        std::string(static_cast<const char*>(buf), size);
  }
  ++seq_counter_;
}

// ---------------------------------------------------------------------------
// checkpointing
// ---------------------------------------------------------------------------

int RobustComm::LoadCheckpoint(std::string* global, std::string* local) {
  OnEngineCall("load_checkpoint");
  if (world_ == 1) {
    if (lazy_global_ != nullptr) {
      global_ckpt_ = *lazy_global_;
      lazy_global_ = nullptr;
    }
    if (global) *global = global_ckpt_;
    if (local) *local = local_ckpt_;
    before_first_load_ = false;
    return version_;
  }
  local_expected_ = (local != nullptr);
  bool served = RecoverExec(nullptr, 0, kLoadCheck, seq_counter_);
  RT_CHECK(served, "load-checkpoint round did not serve the requester");
  if (global) *global = global_ckpt_;
  if (local) *local = local_ckpt_;
  before_first_load_ = false;
  // No ack barrier here: the load is served atomically inside its
  // consensus round, and a barrier flag would wedge the diff-seq replay
  // protocol (a caught-up restarter holds the flag at seq 0 while alive
  // ranks are mid-iteration, and flagged ranks are not replay
  // requesters). The restarter catches up through replay rounds next.
  return version_;
}

void RobustComm::Checkpoint(const std::string& global,
                            const std::string& local) {
  OnEngineCall("checkpoint");
  if (world_ == 1) {
    global_ckpt_ = global;
    local_ckpt_ = local;
    lazy_global_ = nullptr;
    ++version_;
    return;
  }
  // lock in with/without-local mode on first checkpoint (reference
  // LocalModelCheck, allreduce_robust.cc:371-387)
  if (!local_mode_decided_) {
    local_mode_decided_ = true;
    local_expected_ = !local.empty();
    if (!local_expected_) num_local_replica_ = 0;
    if (num_local_replica_ > world_ - 1) num_local_replica_ = world_ - 1;
  }
  // phase 1: everyone reaches the checkpoint fence (returns false when
  // the whole world is at it)
  RecoverExec(nullptr, 0, kCheckPoint, seq_counter_);
  // local replication along the ring
  if (!local.empty() && num_local_replica_ > 0) {
    local_ckpt_ = local;
    for (;;) {
      NetResult res = TryReplicateLocal();
      if (res == NetResult::kOk) break;
      CheckAndRecover(res);
    }
  } else {
    local_ckpt_ = local;
  }
  // commit
  global_ckpt_ = global;
  lazy_global_ = nullptr;
  ++version_;
  result_log_.clear();
  seq_counter_ = 0;
  // phase 2: nobody proceeds until everyone committed (reference
  // two-phase kCheckPoint/kCheckAck, allreduce_robust.cc:436-464)
  RecoverExec(nullptr, 0, kCheckAck, seq_counter_);
}

void RobustComm::LazyCheckpoint(const std::string* global) {
  OnEngineCall("checkpoint");
  if (world_ == 1) {
    lazy_global_ = global;
    ++version_;
    return;
  }
  RecoverExec(nullptr, 0, kCheckPoint, seq_counter_);
  lazy_global_ = global;  // serialization deferred until a failure
  ++version_;
  result_log_.clear();
  seq_counter_ = 0;
  RecoverExec(nullptr, 0, kCheckAck, seq_counter_);
}

// pass my local checkpoint to the next num_local_replica_ ring successors
// (reference TryCheckinLocalState + RingPassing,
// allreduce_robust.cc:1363-1475)
NetResult RobustComm::TryReplicateLocal() {
  replica_local_.assign(static_cast<size_t>(num_local_replica_), "");
  std::string outgoing = local_ckpt_;
  for (int hop = 0; hop < num_local_replica_; ++hop) {
    uint64_t send_len = outgoing.size();
    uint64_t recv_len = 0;
    NetResult res = RingExchange(
        reinterpret_cast<const char*>(&send_len), sizeof(send_len),
        reinterpret_cast<char*>(&recv_len), sizeof(recv_len));
    if (res != NetResult::kOk) return res;
    std::string incoming(recv_len, '\0');
    res = RingExchange(outgoing.data(), outgoing.size(),
                       recv_len ? &incoming[0] : nullptr, recv_len);
    if (res != NetResult::kOk) return res;
    replica_local_[hop] = incoming;  // local state of rank (r-1-hop)
    outgoing = incoming;             // forward it another hop
  }
  return NetResult::kOk;
}

}  // namespace rt
