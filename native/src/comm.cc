#include "comm.h"

#include <algorithm>
#include <chrono>
#include <cstring>
#include <map>
#include <set>
#include <thread>

namespace rt {

static const uint32_t kTrackerMagic = 0x52425401;  // "RBT\x01"
static const uint32_t kLinkMagic = 0x52425402;
static const uint32_t kNoRank = 0xFFFFFFFFu;

Comm::~Comm() { CloseLinks(); }

void Comm::SetupFromConfig(const Config& cfg) {
  tracker_uri_ = cfg.Get("rabit_tracker_uri");
  if (tracker_uri_ == "NULL") tracker_uri_ = "";  // single-node escape,
  // reference allreduce_base.cc:266-268
  tracker_port_ = static_cast<int>(cfg.GetInt("rabit_tracker_port", 9091));
  task_id_ = cfg.Get("rabit_task_id", "0");
  // RABIT_NUM_TRIAL and DMLC_NUM_ATTEMPT (which normalizes to
  // rabit_num_attempt) both name the restart-attempt counter
  num_attempt_ = static_cast<int>(cfg.GetInt(
      "rabit_num_trial", cfg.GetInt("rabit_num_attempt", 0)));
  ring_mincount_ = static_cast<size_t>(
      cfg.GetInt("rabit_reduce_ring_mincount", 32 << 10));
  // explicit setting pins the crossover; only the DEFAULT is subject
  // to the same-host adjustment (see TryAllreduce)
  ring_user_set_ = !cfg.Get("rabit_reduce_ring_mincount").empty();
  reduce_buffer_ = cfg.GetSize("rabit_reduce_buffer", 256u << 20);
  debug_ = cfg.GetBool("rabit_debug", false);
  // an accelerator data plane will be registered after Init (the Python
  // binding calls RbtSetDataPlane post-RbtInit); advertising the intent
  // at registration lets the tracker host a device-world coordinator on
  // demand, whichever way the data plane was requested (argv, env, or
  // the Python engine API)
  std::string dp = cfg.Get("rabit_dataplane", "");
  dataplane_intent_ = !dp.empty() && dp != "none";
  // Hadoop-streaming heartbeat (reference ReportStatus,
  // allreduce_base.h:215-220): emit reporter:status lines on stderr so
  // a streaming scheduler does not kill long recoveries as hung tasks;
  // on by default under Hadoop (mapred env present), opt-in elsewhere
  report_status_ = cfg.GetBool(
      "rabit_report_status", getenv("mapred_tip_id") != nullptr ||
                                 getenv("mapreduce_task_id") != nullptr);
  StopProcessOnError() =
      cfg.GetBool("rabit_stop_process_on_error", false) ||
      // DMLC_WORKER_STOP_PROCESS_ON_ERROR normalizes to this key
      cfg.GetBool("rabit_worker_stop_process_on_error", false);
  // self-healing data plane (doc/fault_tolerance.md): CRC-framed
  // payload hops with hop-local retransmission + in-place link
  // resurrection. Off by default — with the knob unset the wire format
  // and code paths are byte-identical to the unframed engine.
  frame_crc_ = cfg.GetBool("rabit_frame_crc", false);
  frame_retries_ = static_cast<int>(cfg.GetInt("rabit_frame_retries", 4));
  resurrect_ms_ = static_cast<int>(cfg.GetInt("rabit_resurrect_ms", 5000));
  host_ = GetHostName();
}

void Comm::Init(int argc, const char* const* argv) {
  cfg_.LoadEnv();
  cfg_.LoadArgs(argc, argv);
  cfg_.LoadHadoopEnv();  // last: explicit env/argv settings win
  SetupFromConfig(cfg_);
  if (tracker_uri_.empty()) {
    rank_ = 0;
    world_ = 1;
    return;
  }
  ReconnectLinks("start");
}

void Comm::Resize(const char* cmd) {
  if (tracker_uri_.empty()) return;  // single-node: nothing to rewire
  ReconnectLinks(cmd);
}

void Comm::Shutdown() {
  if (tracker_uri_.empty()) return;
  if (links_up_) {
    TcpConn t = ConnectTrackerCmd("shutdown");
    // tracker acks so shutdown is ordered before tracker teardown
    t.RecvU32();
  }
  CloseLinks();
  listener_.Close();
}

void Comm::TrackerPrint(const std::string& msg) {
  if (tracker_uri_.empty()) {
    fprintf(stdout, "%s\n", msg.c_str());
    fflush(stdout);
    return;
  }
  TcpConn t = ConnectTrackerCmd("print");
  t.SendStr(msg);
  t.RecvU32();  // ack
}

TcpConn Comm::ConnectTrackerCmd(const std::string& cmd) {
  // Reference parity (allreduce_base.cc:231-242): absorb transient
  // connection refusals from a tracker that is restarting or saturated
  // by a simultaneous re-registration storm, instead of killing a
  // worker the tracker would have saved. Budget is tunable via
  // rabit_connect_retry / DMLC_WORKER_CONNECT_RETRY (default 5), with
  // the reference's escalating sleep(2*retry) between attempts
  // (~20 s total at the default) — the inner per-attempt retry is
  // disabled for the tracker so this loop owns the whole budget.
  long budget = cfg_.GetInt("rabit_connect_retry",
                            cfg_.GetInt("rabit_worker_connect_retry", 5));
  if (budget < 1) budget = 1;
  // resolve once: only CONNECT refusals are transient — a bad hostname
  // fails identically every attempt and should surface immediately
  std::string addr = TcpConn::ResolveHost(tracker_uri_);
  TcpConn t;
  for (long retry = 1;; ++retry) {
    try {
      t = TcpConn::Connect(addr, tracker_port_, /*retries=*/0);
      break;
    } catch (const rt::Error&) {
      if (retry >= budget) throw;
      rt::LogInfo(rt::StrFormat(
          "retry connect to tracker %s:%d (attempt %ld/%ld)",
          tracker_uri_.c_str(), tracker_port_, retry, budget));
      std::this_thread::sleep_for(std::chrono::seconds(2 * retry));
    }
  }
  t.SendU32(kTrackerMagic);
  t.SendStr(cmd);
  t.SendStr(task_id_);
  t.SendU32(static_cast<uint32_t>(num_attempt_));
  return t;
}

void Comm::CloseLinks() {
  links_.clear();
  tree_idx_.clear();
  parent_pos_ = -1;
  ring_prev_ = ring_next_ = -1;
  links_up_ = false;
}

// Connector side of the link handshake: send magic + own rank, expect
// the peer's rank back. The two non-OK outcomes are deliberately
// distinct: a MISMATCH means we reached a listener that is not our
// peer (stale token) — the real peer's accept loop has seen nothing
// and is still waiting, so retrying over TCP is safe; a DEAD socket
// means the peer itself failed mid-handshake, where a TCP retry could
// arrive after the peer's accept loop already counted this connection
// and exited — connecting into its backlog and hanging forever — so
// death is surfaced to the caller's failure path (recovery) instead.
enum class Handshake { kOk, kMismatch, kDead };
static Handshake LinkHandshake(TcpConn* c, int self_rank, int expect_peer) {
  try {
    c->SendU32(kLinkMagic);
    c->SendU32(static_cast<uint32_t>(self_rank));
    return static_cast<int>(c->RecvU32()) == expect_peer
               ? Handshake::kOk : Handshake::kMismatch;
  } catch (const Error&) {
    return Handshake::kDead;
  }
}

void Comm::ReconnectLinks(const char* cmd) {
  CloseLinks();
  if (listener_.fd() < 0) {
    listener_.Bind(static_cast<int>(cfg_.GetInt("rabit_slave_port", 9010)),
                   1000, cfg_.GetBool("rabit_local_uds", true));
  }
  TcpConn t = ConnectTrackerCmd(cmd);
  t.SendStr(host_);
  t.SendU32(static_cast<uint32_t>(listener_.port()));

  // registration flags: bit 0 advertises data-plane need, so the
  // tracker hosts a device-world coordinator even when the data plane
  // was requested through the Python engine API (invisible to the
  // launcher's argv/env autodetect)
  uint32_t flags = 0;
  if (dataplane_intent_ || dataplane_ != nullptr) flags |= 1u;
  t.SendU32(flags);
  // random name of this listener's UDS twin ("" = TCP-only): the
  // tracker relays it to peers, and only a same-host/same-netns peer
  // can resolve it — the token itself is the same-host proof, so no
  // single-host inference (hostnames, source IPs) gates the fast path
  t.SendStr(cfg_.GetBool("rabit_local_uds", true)
                ? listener_.local_token() : std::string());

  // Assignment (tracker barriers until all world_size workers register,
  // so every peer below is already listening). epoch + coordinator: the
  // tracker hosts one device-world coordination service per registration
  // epoch — it must outlive any worker, because a vanished service
  // fatally poisons surviving clients (see engine/dataplane.py).
  uint32_t prev_epoch = world_epoch_;
  rank_ = static_cast<int>(t.RecvU32());
  world_ = static_cast<int>(t.RecvU32());
  world_epoch_ = t.RecvU32();
  coord_host_ = t.RecvStr();
  coord_port_ = static_cast<int>(t.RecvU32());
  // tracker-computed, hence IDENTICAL on every rank: a per-rank guess
  // from local link addresses could diverge in mixed-host worlds and
  // deadlock a collective on mismatched tree/ring algorithms
  all_local_peers_ = t.RecvU32() != 0;
  uint32_t parent_rank = t.RecvU32();
  uint32_t ntree = t.RecvU32();
  std::vector<int> tree_ranks(ntree);
  for (auto& r : tree_ranks) r = static_cast<int>(t.RecvU32());
  int prev_rank = static_cast<int>(t.RecvU32());
  int next_rank = static_cast<int>(t.RecvU32());

  uint32_t nconnect = t.RecvU32();
  std::map<int, TcpConn> conns;
  // resurrection metadata: how each connect-side link was dialed, so a
  // mid-collective conn death can be repaired in place (ResurrectLink)
  struct PeerAddr { std::string host; int port; std::string token; };
  std::map<int, PeerAddr> peer_addr;
  for (uint32_t i = 0; i < nconnect; ++i) {
    int peer = static_cast<int>(t.RecvU32());
    std::string phost = t.RecvStr();
    int pport = static_cast<int>(t.RecvU32());
    std::string ptoken = t.RecvStr();
    peer_addr[peer] = PeerAddr{phost, pport, ptoken};
    // Same-host peers skip the loopback TCP stack via the peer
    // listener's abstract-UDS twin. The twin's name is a random
    // tracker-relayed token, so resolving it in this netns IS the
    // same-host proof: a cross-host attempt fails instantly (no such
    // name here) and falls back to TCP, per-pair — mixed-host worlds
    // still get UDS between co-located pairs, and no inference
    // (hostname, source IP — both spoofable by clones/SNAT) is
    // trusted. The handshake double-checks the peer's rank: a
    // mismatch (not our peer) retries over TCP; a socket that dies
    // mid-handshake is peer death, owned by the failure path.
    TcpConn c;
    if (cfg_.GetBool("rabit_local_uds", true)) {
      c = TcpConn::ConnectLocal(ptoken);
      if (c.ok()) {
        Handshake hs = LinkHandshake(&c, rank_, peer);
        RT_CHECK(hs != Handshake::kDead,
                 StrFormat("rank %d died during link handshake", peer));
        if (hs != Handshake::kOk) c = TcpConn();  // kMismatch: not our peer
      }
    }
    if (!c.ok()) {
      c = TcpConn::Connect(phost, pport);
      RT_CHECK(LinkHandshake(&c, rank_, peer) == Handshake::kOk,
               StrFormat("link handshake with rank %d failed", peer));
    }
    conns.emplace(peer, std::move(c));
  }
  // the tracker's naccept equals our higher-ranked neighbors; derive
  // the expected set locally so an inbound claim can be validated
  std::set<int> expect_accept;
  for (int r : tree_ranks) if (r > rank_) expect_accept.insert(r);
  if (world_ > 1) {
    if (prev_rank > rank_) expect_accept.insert(prev_rank);
    if (next_rank > rank_) expect_accept.insert(next_rank);
  }
  uint32_t naccept = t.RecvU32();
  RT_CHECK(expect_accept.size() == naccept,
           StrFormat("tracker naccept %u != expected neighbor count %zu",
                     naccept, expect_accept.size()));
  for (uint32_t accepted = 0; accepted < naccept;) {
    TcpConn c = listener_.Accept();
    // A bogus inbound connection (bad magic, unexpected rank, dies
    // mid-handshake) is dropped without consuming an accept slot:
    // aborting here — or counting it — would let one stray connect
    // wedge the whole world. A REPEATED expected rank (peer abandoned
    // a suspect connection and redialed) replaces the stale conn
    // without recounting, so the loop still waits for every real peer.
    uint32_t magic = 0, prank = 0;
    try {
      magic = c.RecvU32();
      if (magic != kLinkMagic) continue;
      prank = c.RecvU32();
      c.SendU32(static_cast<uint32_t>(rank_));
    } catch (const Error&) {
      continue;
    }
    int pr = static_cast<int>(prank);
    if (!expect_accept.count(pr)) continue;
    bool fresh = conns.find(pr) == conns.end();
    conns[pr] = std::move(c);  // newest wins: older twin was abandoned
    if (fresh) ++accepted;
  }
  // Epoch advanced while a device world may be formed: tell the data
  // plane to drop its old client NOW, before the ready ack. Ordering
  // contract with the tracker: once every member of the new epoch has
  // acked, no client of any older epoch exists, so the tracker can reap
  // old coordination services without poisoning a live client.
  if (dataplane_ != nullptr && prev_epoch != 0 && world_epoch_ != prev_epoch) {
    dataplane_(nullptr, 0, -1, -1, world_epoch_, dataplane_ctx_);
  }
  // ready ack: tracker knows this worker finished wiring
  t.SendU32(1u);

  // index links
  for (auto& kv : conns) {
    Link l;
    l.peer_rank = kv.first;
    l.conn = std::move(kv.second);
    l.conn.SetKeepAlive();
    auto pa = peer_addr.find(kv.first);
    if (pa != peer_addr.end()) {
      // we dialed this peer; a dead conn is repaired by redialing
      l.i_connect = true;
      l.peer_host = pa->second.host;
      l.peer_port = pa->second.port;
      l.peer_token = pa->second.token;
    }
    links_.push_back(std::move(l));
  }
  auto find_link = [&](int r) {
    for (size_t i = 0; i < links_.size(); ++i)
      if (links_[i].peer_rank == r) return static_cast<int>(i);
    Fail(StrFormat("rank %d not among established links", r));
    return -1;
  };
  for (int r : tree_ranks) tree_idx_.push_back(find_link(r));
  if (parent_rank != kNoRank) {
    for (size_t i = 0; i < tree_ranks.size(); ++i)
      if (tree_ranks[i] == static_cast<int>(parent_rank))
        parent_pos_ = static_cast<int>(i);
    RT_CHECK(parent_pos_ >= 0, "parent not in tree neighbor list");
  } else {
    parent_pos_ = -1;
  }
  if (world_ > 1) {
    ring_prev_ = find_link(prev_rank);
    ring_next_ = find_link(next_rank);
  }
  for (auto& l : links_) l.conn.SetNonBlocking(true);
  links_up_ = true;
  if (debug_) {
    LogInfo(StrFormat("rank %d/%d links up (%zu links, parent %s)", rank_,
                      world_, links_.size(),
                      parent_pos_ < 0 ? "none" : "yes"));
  }
}

// Hadoop-streaming heartbeat (reference ReportStatus,
// allreduce_base.h:215-220, emitted each recovery round at
// allreduce_robust.cc:1062): the reporter:status: prefix on stderr is
// the streaming protocol's "task is alive" signal.
void Comm::ReportStatus(const char* phase, uint32_t seq) const {
  if (!report_status_) return;
  fprintf(stderr, "reporter:status:Rabit Phase[%d] %s seq %u\n", version_,
          phase, seq);
  fflush(stderr);
}

// ---------------------------------------------------------------------------
// Collectives
// ---------------------------------------------------------------------------

void Comm::Allreduce(void* buf, size_t elem_size, size_t count,
                     ReduceFn reducer, PrepareFn prepare, void* prepare_arg,
                     const char*, int dtype, int op) {
  if (prepare != nullptr) prepare(prepare_arg);
  NetResult r = ExecuteAllreduce(buf, elem_size, count, reducer, dtype, op);
  RT_CHECK(r == NetResult::kOk, "allreduce failed (no recovery in base "
                                "engine; use the robust engine)");
}

NetResult Comm::ExecuteAllreduce(void* buf, size_t elem_size, size_t count,
                                 ReduceFn reducer, int dtype, int op) {
  if (world_ > 1 && dataplane_ != nullptr && dtype >= 0 && op >= 0 &&
      elem_size * count >= dataplane_minbytes_ && count > 0) {
    int rc = dataplane_(buf, static_cast<uint64_t>(count), dtype, op,
                        world_epoch_, dataplane_ctx_);
    if (rc == 0) return NetResult::kOk;
    // device-plane failure looks like a link failure to the caller: the
    // robust engine reconnects (advancing the epoch) and retries
    return NetResult::kReset;
  }
  return TryAllreduce(buf, elem_size, count, reducer);
}

void Comm::Broadcast(void* buf, size_t size, int root, const char*) {
  NetResult r = TryBroadcast(static_cast<char*>(buf), size, root);
  RT_CHECK(r == NetResult::kOk, "broadcast failed (no recovery in base "
                                "engine; use the robust engine)");
}

int Comm::LoadCheckpoint(std::string* global, std::string* local) {
  if (global) global->clear();
  if (local) local->clear();
  return 0;  // base engine: not fault tolerant (like engine_mpi.cc:47-60)
}

void Comm::Checkpoint(const std::string&, const std::string&) {
  ++version_;
}

void Comm::LazyCheckpoint(const std::string*) { ++version_; }

NetResult Comm::TryAllreduce(void* buf, size_t elem_size, size_t count,
                             ReduceFn reducer) {
  if (world_ == 1 || count == 0) return NetResult::kOk;
  // the crossover the reference documents but never wires (SURVEY §2 #3);
  // same-host worlds default to the streaming tree at every size (links
  // share one medium — see ReconnectLinks), unless the user pinned the
  // crossover explicitly
  if (count >= ring_mincount_ && world_ > 2 &&
      (ring_user_set_ || !all_local_peers_)) {
    return TryAllreduceRing(static_cast<char*>(buf), elem_size, count,
                            reducer);
  }
  return TryAllreduceTree(static_cast<char*>(buf), elem_size, count, reducer);
}

// Streaming tree allreduce: reduce up from children while broadcasting
// results down from the root, all links nonblocking under one poll loop
// (reference TryAllreduceTree, allreduce_base.cc:475-640). Down-writes
// into buf are safe because result byte i only arrives after byte i was
// sent up (same invariant as the reference's single-buffer design).
NetResult Comm::TryAllreduceTree(char* buf, size_t elem_size, size_t count,
                                 ReduceFn reducer) {
  if (frame_crc_) return TryAllreduceTreeFramed(buf, elem_size, count,
                                                reducer);
  const size_t total = elem_size * count;
  std::vector<int> children;
  int parent_link = -1;
  for (size_t i = 0; i < tree_idx_.size(); ++i) {
    if (static_cast<int>(i) == parent_pos_) parent_link = tree_idx_[i];
    else children.push_back(tree_idx_[i]);
  }
  // segment boundary must be element-aligned or the fold can never
  // reach S (and the next segment would start mid-element); the scratch
  // budget (reduce_buffer_) covers ALL per-child buffers together, so
  // divide by the child count before sizing a segment
  const size_t per_child =
      reduce_buffer_ / std::max<size_t>(children.size(), 1);
  const size_t seg_max =
      std::max<size_t>(per_child / elem_size, 1) * elem_size;
  std::vector<std::vector<char>> cbuf(children.size());
  for (auto& b : cbuf) b.resize(std::min<size_t>(seg_max, total));

  for (size_t seg_off = 0; seg_off < total; seg_off += seg_max) {
    const size_t S = std::min(seg_max, total - seg_off);
    char* base = buf + seg_off;
    std::vector<size_t> crecv(children.size(), 0);
    size_t reduced = children.empty() ? S : 0;
    size_t sent_up = 0;
    size_t down_recv = (parent_link < 0) ? reduced : 0;
    std::vector<size_t> down_sent(children.size(), 0);

    auto done = [&]() {
      if (down_recv < S) return false;
      for (size_t c = 0; c < children.size(); ++c)
        if (down_sent[c] < S) return false;
      if (parent_link >= 0 && sent_up < S) return false;
      return true;
    };

    while (!done()) {
      if (TakeInterrupt()) return NetResult::kInterrupt;
      Poller poll;
      bool watching = false;
      for (size_t c = 0; c < children.size(); ++c) {
        if (crecv[c] < S) {
          poll.WatchRead(links_[children[c]].conn.fd());
          watching = true;
        }
        if (down_sent[c] < down_recv) {
          poll.WatchWrite(links_[children[c]].conn.fd());
          watching = true;
        }
      }
      if (parent_link >= 0) {
        if (sent_up < reduced) {
          poll.WatchWrite(links_[parent_link].conn.fd());
          watching = true;
        }
        if (down_recv < S) {
          poll.WatchRead(links_[parent_link].conn.fd());
          watching = true;
        }
      }
      if (watching) {
        // bounded wait so an out-of-band interrupt (watchdog reform
        // rung) is observed within ~500ms even on a fully wedged link
        if (poll.Wait(500) < 0) return NetResult::kError;
      }
      NetResult res;
      // children -> us (reduce direction)
      for (size_t c = 0; c < children.size(); ++c) {
        auto& conn = links_[children[c]].conn;
        if (crecv[c] < S && poll.CanRead(conn.fd())) {
          ssize_t k = conn.TryRecv(cbuf[c].data() + crecv[c], S - crecv[c],
                                   &res);
          if (k < 0) return res;
          crecv[c] += static_cast<size_t>(k);
        }
      }
      // fold newly complete region
      if (!children.empty()) {
        size_t minc = S;
        for (size_t c = 0; c < children.size(); ++c)
          minc = std::min(minc, crecv[c]);
        size_t aligned = (minc / elem_size) * elem_size;
        if (aligned > reduced) {
          for (size_t c = 0; c < children.size(); ++c) {
            reducer(base + reduced, cbuf[c].data() + reduced,
                    (aligned - reduced) / elem_size);
          }
          reduced = aligned;
        }
      }
      if (parent_link < 0) {
        down_recv = reduced;  // root: result is the reduced prefix
      } else {
        auto& pconn = links_[parent_link].conn;
        if (sent_up < reduced && poll.CanWrite(pconn.fd())) {
          ssize_t k = pconn.TrySend(base + sent_up, reduced - sent_up, &res);
          if (k < 0) return res;
          sent_up += static_cast<size_t>(k);
        }
        if (down_recv < S && sent_up > down_recv &&
            poll.CanRead(pconn.fd())) {
          // result bytes never outrun what we sent up
          ssize_t k = pconn.TryRecv(base + down_recv, sent_up - down_recv,
                                    &res);
          if (k < 0) return res;
          down_recv += static_cast<size_t>(k);
        }
      }
      // us -> children (broadcast direction)
      for (size_t c = 0; c < children.size(); ++c) {
        auto& conn = links_[children[c]].conn;
        if (down_sent[c] < down_recv && poll.CanWrite(conn.fd())) {
          ssize_t k = conn.TrySend(base + down_sent[c],
                                   down_recv - down_sent[c], &res);
          if (k < 0) return res;
          down_sent[c] += static_cast<size_t>(k);
        }
      }
    }
  }
  return NetResult::kOk;
}

// Tree broadcast with dynamic in-link discovery: whichever tree neighbor
// sends first is upstream; forward chunks to every other tree link as
// they arrive (reference TryBroadcast, allreduce_base.cc:649-737).
NetResult Comm::TryBroadcast(char* buf, size_t size, int root) {
  if (world_ == 1 || size == 0) return NetResult::kOk;
  if (frame_crc_) {
    // framed broadcast = framed routed multicast with need=everyone.
    // The dynamic in-link discovery below is incompatible with
    // stop-and-wait framing (the first frame would be consumed before
    // the in-link is known), so the framed path uses the static
    // binary-tree plan every rank derives identically.
    std::vector<uint8_t> need(world_, 1);
    return TryRouteDataFramed(buf, size, root, need);
  }
  const bool is_root = (rank_ == root);
  int in_link = is_root ? -2 : -1;  // -2: we originate; -1: unknown yet
  size_t recvd = is_root ? size : 0;
  std::vector<size_t> sent(tree_idx_.size(), 0);

  auto done = [&]() {
    if (recvd < size) return false;
    for (size_t i = 0; i < tree_idx_.size(); ++i) {
      if (static_cast<int>(i) == in_link) continue;
      if (sent[i] < size) return false;
    }
    return true;
  };

  while (!done()) {
    if (TakeInterrupt()) return NetResult::kInterrupt;
    Poller poll;
    for (size_t i = 0; i < tree_idx_.size(); ++i) {
      auto& conn = links_[tree_idx_[i]].conn;
      if (in_link == -1) poll.WatchRead(conn.fd());
      if (static_cast<int>(i) == in_link && recvd < size)
        poll.WatchRead(conn.fd());
      if (static_cast<int>(i) != in_link && sent[i] < recvd)
        poll.WatchWrite(conn.fd());
    }
    if (poll.Wait(500) < 0) return NetResult::kError;
    NetResult res;
    if (in_link == -1) {
      for (size_t i = 0; i < tree_idx_.size(); ++i) {
        auto& conn = links_[tree_idx_[i]].conn;
        if (poll.CanRead(conn.fd())) {
          ssize_t k = conn.TryRecv(buf, size, &res);
          if (k < 0) return res;
          if (k > 0) {
            in_link = static_cast<int>(i);
            recvd = static_cast<size_t>(k);
            break;
          }
        }
      }
    } else if (in_link >= 0 && recvd < size) {
      auto& conn = links_[tree_idx_[in_link]].conn;
      if (poll.CanRead(conn.fd())) {
        ssize_t k = conn.TryRecv(buf + recvd, size - recvd, &res);
        if (k < 0) return res;
        recvd += static_cast<size_t>(k);
      }
    }
    for (size_t i = 0; i < tree_idx_.size(); ++i) {
      if (static_cast<int>(i) == in_link) continue;
      auto& conn = links_[tree_idx_[i]].conn;
      if (sent[i] < recvd && poll.CanWrite(conn.fd())) {
        ssize_t k = conn.TrySend(buf + sent[i], recvd - sent[i], &res);
        if (k < 0) return res;
        sent[i] += static_cast<size_t>(k);
      }
    }
  }
  return NetResult::kOk;
}

// Targeted multicast along the deterministic complete binary tree
// (tracker topology: parent=(r-1)/2). Every rank derives the same plan
// from (src_rank, need): re-root the tree at src, keep only edges on a
// src->requester path, stream with the same chunked forwarding as
// TryBroadcast. O(world) plan time (process count, not data); traffic
// O(size x subtree edges).
NetResult Comm::TryRouteData(char* buf, size_t size, int src_rank,
                             const std::vector<uint8_t>& need) {
  if (world_ == 1 || size == 0) return NetResult::kOk;
  if (frame_crc_) return TryRouteDataFramed(buf, size, src_rank, need);
  const int P = world_;
  bool any = false;
  for (int r = 0; r < P; ++r) any = any || (need[r] != 0);
  if (!any) return NetResult::kOk;
  // BFS from src over tree edges: toward[r] = r's neighbor on the path
  // to src; order[] has parents (src side) before children
  std::vector<int> toward(P, -1), order;
  std::vector<uint8_t> seen(P, 0), sub(P, 0);
  order.reserve(P);
  order.push_back(src_rank);
  seen[src_rank] = 1;
  for (size_t i = 0; i < order.size(); ++i) {
    int u = order[i];
    int nb[3] = {u > 0 ? (u - 1) / 2 : -1, 2 * u + 1, 2 * u + 2};
    for (int v : nb) {
      if (v < 0 || v >= P || seen[v]) continue;
      seen[v] = 1;
      toward[v] = u;
      order.push_back(v);
    }
  }
  // sub[r]: r's src-rooted subtree contains a requester (incl. r itself)
  for (size_t i = order.size(); i-- > 0;) {
    int u = order[i];
    if (need[u]) sub[u] = 1;
    if (sub[u] && toward[u] >= 0) sub[toward[u]] = 1;
  }
  const bool is_src = (rank_ == src_rank);
  if (!is_src && !sub[rank_]) return NetResult::kOk;  // off every path

  auto link_of = [&](int peer) {
    for (size_t i = 0; i < links_.size(); ++i)
      if (links_[i].peer_rank == peer) return static_cast<int>(i);
    Fail(StrFormat("route peer %d not among links", peer));
    return -1;
  };
  int in_link = is_src ? -1 : link_of(toward[rank_]);
  std::vector<int> out_links;
  int my_nb[3] = {rank_ > 0 ? (rank_ - 1) / 2 : -1, 2 * rank_ + 1,
                  2 * rank_ + 2};
  for (int v : my_nb) {
    if (v < 0 || v >= P || toward[v] != rank_) continue;
    if (sub[v]) out_links.push_back(link_of(v));
  }

  // stream: recv from in_link (src: already has data), forward chunks to
  // out_links as they arrive — TryBroadcast's loop on the plan's links
  std::vector<char> scratch;
  char* data = buf;
  if (!is_src && !need[rank_]) {
    scratch.resize(size);
    data = scratch.data();
  }
  size_t recvd = is_src ? size : 0;
  std::vector<size_t> sent(out_links.size(), 0);
  auto done = [&]() {
    if (recvd < size) return false;
    for (size_t i = 0; i < out_links.size(); ++i)
      if (sent[i] < size) return false;
    return true;
  };
  while (!done()) {
    if (TakeInterrupt()) return NetResult::kInterrupt;
    Poller poll;
    if (in_link >= 0 && recvd < size)
      poll.WatchRead(links_[in_link].conn.fd());
    for (size_t i = 0; i < out_links.size(); ++i)
      if (sent[i] < recvd) poll.WatchWrite(links_[out_links[i]].conn.fd());
    if (poll.Wait(500) < 0) return NetResult::kError;
    NetResult res;
    if (in_link >= 0 && recvd < size &&
        poll.CanRead(links_[in_link].conn.fd())) {
      ssize_t k = links_[in_link].conn.TryRecv(data + recvd, size - recvd,
                                               &res);
      if (k < 0) return res;
      recvd += static_cast<size_t>(k);
    }
    for (size_t i = 0; i < out_links.size(); ++i) {
      auto& conn = links_[out_links[i]].conn;
      if (sent[i] < recvd && poll.CanWrite(conn.fd())) {
        ssize_t k = conn.TrySend(data + sent[i], recvd - sent[i], &res);
        if (k < 0) return res;
        sent[i] += static_cast<size_t>(k);
      }
    }
  }
  return NetResult::kOk;
}

std::vector<size_t> Comm::RingRanges(size_t count, size_t elem_size) const {
  std::vector<size_t> off(world_ + 1, 0);
  size_t base = count / world_, rem = count % world_;
  for (int r = 0; r < world_; ++r) {
    size_t n = base + (static_cast<size_t>(r) < rem ? 1 : 0);
    off[r + 1] = off[r] + n * elem_size;
  }
  return off;
}

NetResult Comm::RingExchange(const char* send_buf, size_t send_n,
                             char* recv_buf, size_t recv_n) {
  if (frame_crc_) return FramedRingExchange(send_buf, send_n,
                                            recv_buf, recv_n);
  auto& next = links_[ring_next_].conn;
  auto& prev = links_[ring_prev_].conn;
  size_t sent = 0, recvd = 0;
  while (sent < send_n || recvd < recv_n) {
    if (TakeInterrupt()) return NetResult::kInterrupt;
    Poller poll;
    if (sent < send_n) poll.WatchWrite(next.fd());
    if (recvd < recv_n) poll.WatchRead(prev.fd());
    if (poll.Wait(500) < 0) return NetResult::kError;
    NetResult res;
    if (sent < send_n && poll.CanWrite(next.fd())) {
      ssize_t k = next.TrySend(send_buf + sent, send_n - sent, &res);
      if (k < 0) return res;
      sent += static_cast<size_t>(k);
    }
    if (recvd < recv_n && poll.CanRead(prev.fd())) {
      ssize_t k = prev.TryRecv(recv_buf + recvd, recv_n - recvd, &res);
      if (k < 0) return res;
      recvd += static_cast<size_t>(k);
    }
  }
  return NetResult::kOk;
}

// Ring reduce-scatter: world-1 neighbor exchanges; after step s rank r
// has accumulated s+2 contributions into range (r-s-2) mod P; rank r
// ends owning range r fully reduced (reference TryReduceScatterRing,
// allreduce_base.cc:829-918 — ownership offset differs; ours lands the
// reduced range on its own rank index).
NetResult Comm::TryReduceScatterRing(char* buf, size_t elem_size,
                                     size_t count, ReduceFn reducer) {
  const int P = world_;
  auto off = RingRanges(count, elem_size);
  std::vector<char> tmp(off[1] - off[0] + elem_size);
  for (int s = 0; s < P - 1; ++s) {
    int send_r = ((rank_ - s - 1) % P + P) % P;
    int recv_r = ((rank_ - s - 2) % P + P) % P;
    size_t send_n = off[send_r + 1] - off[send_r];
    size_t recv_n = off[recv_r + 1] - off[recv_r];
    if (tmp.size() < recv_n) tmp.resize(recv_n);
    NetResult res = RingExchange(buf + off[send_r], send_n, tmp.data(),
                                 recv_n);
    if (res != NetResult::kOk) return res;
    if (recv_n > 0) {
      reducer(buf + off[recv_r], tmp.data(), recv_n / elem_size);
    }
  }
  return NetResult::kOk;
}

// Ring all-gather: rank r starts owning range r; world-1 forwarding steps
// (reference TryAllgatherRing, allreduce_base.cc:751-815).
NetResult Comm::TryAllgatherRing(char* buf, size_t elem_size, size_t count) {
  const int P = world_;
  auto off = RingRanges(count, elem_size);
  for (int s = 0; s < P - 1; ++s) {
    int send_r = ((rank_ - s) % P + P) % P;
    int recv_r = ((rank_ - s - 1) % P + P) % P;
    NetResult res = RingExchange(buf + off[send_r],
                                 off[send_r + 1] - off[send_r],
                                 buf + off[recv_r],
                                 off[recv_r + 1] - off[recv_r]);
    if (res != NetResult::kOk) return res;
  }
  return NetResult::kOk;
}

NetResult Comm::TryAllreduceRing(char* buf, size_t elem_size, size_t count,
                                 ReduceFn reducer) {
  NetResult res = TryReduceScatterRing(buf, elem_size, count, reducer);
  if (res != NetResult::kOk) return res;
  return TryAllgatherRing(buf, elem_size, count);
}

// ---------------------------------------------------------------------------
// Framed data plane (rabit_frame_crc=1): every payload hop becomes a
// stop-and-wait [magic|seq|len|crc]+payload frame answered by an
// ACK/NAK verdict. A corrupt frame is rejected and retransmitted
// hop-local — corrupt bytes are never folded into the reduction or
// forwarded downstream. A conn death mid-frame is repaired in place
// (ResurrectLink): the fresh connection carries a seq handshake that
// resolves whether the in-flight frame was delivered, so a repaired
// link neither loses nor double-applies a frame. Remaining holes are
// deliberately bounded, not closed: a bit flip landing in a frame
// HEADER (24 bytes vs kFrameChunk of payload) can desync the byte
// stream, and a corrupted verdict can strand a retransmission — both
// exhaust frame_retries_ (or trip a parse check) and surface as
// kReset, which the robust layer's existing global recovery
// (ReconnectLinks + replay) already handles.
// ---------------------------------------------------------------------------

static const uint32_t kFrameMagic = 0x52425446;    // "RBTF"
static const uint32_t kVerdictMagic = 0x52425456;  // "RBTV"
static const uint32_t kVerdictAck = 1;
static const uint32_t kVerdictNak = 0;
// compile-time frame payload cap: both ends derive identical chunking
// from sizes they already agree on, so no config-skew can desync it
static const size_t kFrameChunk = 1u << 20;
// scale-sidecar cap: int8 ships one f32 scale per block, so even a
// degenerate 2-element block stays under 2x payload; anything larger
// in the header is corruption, not configuration
static const size_t kFrameScalesMax = kFrameChunk * 2;

// FrameHeader / FrameWireMeta live in comm.h (the selftest checks the
// wire layout); only the verdict message is private to this file.
struct VerdictMsg {
  uint32_t magic, seq, code;
};

NetResult Comm::FramedStep(int out_li, const char* sbuf, size_t sn,
                           int in_li, char* rbuf, size_t rn,
                           const FrameWireMeta* wm,
                           std::vector<char>* rscales) {
  bool send_done = (out_li < 0);
  bool recv_done = (in_li < 0);
  if (send_done && recv_done) return NetResult::kOk;
  int snaks = 0, rnaks = 0;

  // per-link IO state; out_li == in_li (2-rank ring) shares one stream
  struct LinkIO {
    std::vector<char> out;   // complete messages, appended in order
    size_t out_off = 0;
    enum State { kMagicSt, kFrameSt, kVerdictSt, kPayloadSt } st = kMagicSt;
    char hdr[sizeof(FrameHeader)];
    size_t hdr_got = 0;
    FrameHeader fh{};
    std::vector<char> payload;
    size_t pay_got = 0;
    void ResetParse() { st = kMagicSt; hdr_got = 0; pay_got = 0; }
  };
  std::vector<int> ls;
  if (out_li >= 0) ls.push_back(out_li);
  if (in_li >= 0 && in_li != out_li) ls.push_back(in_li);
  std::vector<LinkIO> io(ls.size());
  auto io_of = [&](int li) -> LinkIO& {
    return io[(ls.size() == 2 && li == ls[1]) ? 1 : 0];
  };

  auto enqueue_frame = [&]() {
    LinkIO& o = io_of(out_li);
    FrameHeader h;
    h.magic = kFrameMagic;
    h.seq = links_[out_li].send_seq;
    h.len = static_cast<uint32_t>(sn);
    if (wm != nullptr && wm->codec != kFrameWireNone) {
      h.wire_codec = wm->codec;
      h.block_log2 = wm->block_log2;
      h.scales_len = wm->scales_len;
    }
    // one CRC over sidecar then payload: a flipped scale bit rejects
    // (and retransmits) the whole frame, same as a payload flip
    uint32_t c = Crc32Begin();
    if (h.scales_len != 0) c = Crc32Feed(c, wm->scales, h.scales_len);
    c = Crc32Feed(c, sbuf, sn);
    h.crc = Crc32End(c);
    const char* hp = reinterpret_cast<const char*>(&h);
    o.out.insert(o.out.end(), hp, hp + sizeof(h));
    if (h.scales_len != 0)
      o.out.insert(o.out.end(), wm->scales, wm->scales + h.scales_len);
    o.out.insert(o.out.end(), sbuf, sbuf + sn);
  };
  auto enqueue_verdict = [&](int li, uint32_t seq, uint32_t code) {
    LinkIO& o = io_of(li);
    VerdictMsg v{kVerdictMagic, seq, code};
    const char* vp = reinterpret_cast<const char*>(&v);
    o.out.insert(o.out.end(), vp, vp + sizeof(v));
  };
  if (!send_done) enqueue_frame();

  // conn death: repair in place, then recompute direction doneness from
  // the seqs exchanged in the resurrection handshake — the fresh stream
  // starts clean, so no partial frame/verdict bytes survive
  auto repair = [&](int li) -> bool {
    if (!ResurrectLink(li)) return false;
    LinkIO& o = io_of(li);
    o.out.clear();
    o.out_off = 0;
    o.ResetParse();
    if (li == out_li && !send_done) {
      Link& l = links_[out_li];
      if (l.peer_recv_seq > l.send_seq) {
        ++l.send_seq;  // in-flight frame was already accepted
        send_done = true;
      } else {
        enqueue_frame();
      }
    }
    // recv side: if we had accepted (recv_seq advanced pre-ack) the
    // peer learned it from the handshake; otherwise it resends
    return true;
  };

  // a completed inbound frame on in_li
  auto handle_frame = [&](int li, const FrameHeader& fh,
                          const char* pay) -> NetResult {
    if (li != in_li) return NetResult::kReset;  // frame on a verdict link
    Link& l = links_[li];
    if (fh.seq < l.recv_seq) {  // dup (our earlier ack was lost): re-ack
      enqueue_verdict(li, fh.seq, kVerdictAck);
      return NetResult::kOk;
    }
    if (fh.seq != l.recv_seq || recv_done) return NetResult::kReset;
    // pay holds sidecar + payload contiguously — one CRC covers both,
    // so a corrupt scale is NAKed and retransmitted like corrupt data
    if (Crc32(pay, static_cast<size_t>(fh.scales_len) + fh.len) != fh.crc) {
      ++stat_frame_rejects_;
      enqueue_verdict(li, l.recv_seq, kVerdictNak);
      return ++rnaks > frame_retries_ ? NetResult::kReset : NetResult::kOk;
    }
    if (fh.len != rn) return NetResult::kReset;  // plan skew: not healable
    if (fh.wire_codec != kFrameWireNone) {
      // quantized frame at a receiver with no sidecar sink: the two
      // ends disagree on the wire plan — not healable by retransmit
      if (rscales == nullptr) return NetResult::kReset;
      rscales->assign(pay, pay + fh.scales_len);
    } else if (rscales != nullptr) {
      rscales->clear();
    }
    memcpy(rbuf, pay + fh.scales_len, rn);
    ++l.recv_seq;  // advance BEFORE acking: the resurrection handshake
                   // then proves delivery even when the ack is lost
    recv_done = true;
    enqueue_verdict(li, fh.seq, kVerdictAck);
    return NetResult::kOk;
  };

  auto handle_verdict = [&](int li, const VerdictMsg& v) -> NetResult {
    if (li != out_li) return NetResult::kReset;
    if (send_done) return NetResult::kOk;  // stale: already confirmed
    if (v.code == kVerdictAck && v.seq == links_[li].send_seq) {
      ++links_[li].send_seq;
      send_done = true;
      return NetResult::kOk;
    }
    // NAK — or a verdict whose fields the fault corrupted: retransmit
    // either way; a re-sent frame the peer actually accepted is just a
    // dup it re-acks, so over-retransmitting converges
    if (++snaks > frame_retries_) return NetResult::kReset;
    enqueue_frame();
    return NetResult::kOk;
  };

  auto all_done = [&]() {
    if (!send_done || !recv_done) return false;
    for (auto& o : io)
      if (o.out_off < o.out.size()) return false;
    return true;
  };

  while (!all_done()) {
    if (TakeInterrupt()) return NetResult::kInterrupt;
    Poller poll;
    for (size_t x = 0; x < ls.size(); ++x) {
      if (io[x].out_off < io[x].out.size())
        poll.WatchWrite(links_[ls[x]].conn.fd());
      bool want_read = (ls[x] == in_li && !recv_done) ||
                       (ls[x] == out_li && !send_done);
      if (want_read) poll.WatchRead(links_[ls[x]].conn.fd());
    }
    if (poll.Wait(500) < 0) return NetResult::kError;
    for (size_t x = 0; x < ls.size(); ++x) {
      int li = ls[x];
      LinkIO& o = io[x];
      NetResult res;
      if (o.out_off < o.out.size() &&
          poll.CanWrite(links_[li].conn.fd())) {
        ssize_t k = links_[li].conn.TrySend(o.out.data() + o.out_off,
                                            o.out.size() - o.out_off, &res);
        if (k < 0) {
          if (res == NetResult::kError) return res;
          if (!repair(li)) return NetResult::kReset;
          continue;  // fresh conn, stale poll results: re-poll
        }
        o.out_off += static_cast<size_t>(k);
        if (o.out_off == o.out.size()) {
          o.out.clear();
          o.out_off = 0;
        }
      }
      bool want_read = (li == in_li && !recv_done) ||
                       (li == out_li && !send_done);
      if (!want_read || !poll.CanRead(links_[li].conn.fd())) continue;
      // pump available bytes through the message parser
      for (bool progress = true; progress;) {
        progress = false;
        size_t need = 0;
        char* dst = nullptr;
        switch (o.st) {
          case LinkIO::kMagicSt: need = 4; dst = o.hdr; break;
          case LinkIO::kFrameSt: need = sizeof(FrameHeader); dst = o.hdr;
            break;
          case LinkIO::kVerdictSt: need = sizeof(VerdictMsg); dst = o.hdr;
            break;
          case LinkIO::kPayloadSt:
            need = static_cast<size_t>(o.fh.scales_len) + o.fh.len;
            dst = o.payload.data();
            break;
        }
        size_t* got = (o.st == LinkIO::kPayloadSt) ? &o.pay_got : &o.hdr_got;
        if (*got < need) {
          ssize_t k = links_[li].conn.TryRecv(dst + *got, need - *got, &res);
          if (k < 0) {
            if (res == NetResult::kError) return res;
            if (!repair(li)) return NetResult::kReset;
            break;
          }
          if (k == 0) break;  // kAgain: kernel buffer drained
          *got += static_cast<size_t>(k);
          progress = true;
        }
        if (*got < need) continue;
        // a complete unit: advance the parser state machine
        switch (o.st) {
          case LinkIO::kMagicSt: {
            uint32_t magic = 0;
            memcpy(&magic, o.hdr, 4);
            if (magic == kFrameMagic) o.st = LinkIO::kFrameSt;
            else if (magic == kVerdictMagic) o.st = LinkIO::kVerdictSt;
            else return NetResult::kReset;  // stream desync
            progress = true;
            break;
          }
          case LinkIO::kFrameSt: {
            memcpy(&o.fh, o.hdr, sizeof(o.fh));
            // wire-metadata sanity gates BEFORE sizing any buffer: a
            // corrupted header must not allocate unbounded payload or
            // smuggle a sidecar into an unquantized frame
            if (o.fh.len > kFrameChunk) return NetResult::kReset;
            if (o.fh.scales_len > kFrameScalesMax) return NetResult::kReset;
            if (o.fh.wire_codec > kFrameWireInt8) return NetResult::kReset;
            if (o.fh.wire_codec != kFrameWireInt8 && o.fh.scales_len != 0)
              return NetResult::kReset;
            if (o.fh.wire_codec == kFrameWireNone && o.fh.block_log2 != 0)
              return NetResult::kReset;
            o.payload.resize(static_cast<size_t>(o.fh.scales_len) + o.fh.len);
            o.pay_got = 0;
            o.st = LinkIO::kPayloadSt;
            progress = true;
            break;
          }
          case LinkIO::kPayloadSt: {
            NetResult r = handle_frame(li, o.fh, o.payload.data());
            if (r != NetResult::kOk) return r;
            o.ResetParse();
            progress = true;
            break;
          }
          case LinkIO::kVerdictSt: {
            VerdictMsg v{};
            memcpy(&v, o.hdr, sizeof(v));
            NetResult r = handle_verdict(li, v);
            if (r != NetResult::kOk) return r;
            o.ResetParse();
            progress = true;
            break;
          }
        }
        // stop reading the moment this link owes us nothing more —
        // bytes of the NEXT collective's frames stay in the kernel
        bool still = (li == in_li && !recv_done) ||
                     (li == out_li && !send_done);
        if (!still) break;
      }
    }
  }
  return NetResult::kOk;
}

NetResult Comm::FramedSendLink(int li, const char* buf, size_t n) {
  size_t off = 0;
  while (off < n) {
    size_t k = std::min(kFrameChunk, n - off);
    NetResult r = FramedStep(li, buf + off, k, -1, nullptr, 0);
    if (r != NetResult::kOk) return r;
    off += k;
  }
  return NetResult::kOk;
}

NetResult Comm::FramedRecvLink(int li, char* buf, size_t n) {
  size_t off = 0;
  while (off < n) {
    size_t k = std::min(kFrameChunk, n - off);
    NetResult r = FramedStep(-1, nullptr, 0, li, buf + off, k);
    if (r != NetResult::kOk) return r;
    off += k;
  }
  return NetResult::kOk;
}

// duplex frame pipeline with the ring neighbors: one frame each way per
// step until both directions are exhausted. Chunk sizes on each side
// are derived from the range sizes the ring algorithm already agrees
// on, so sender and receiver compute identical frame sequences.
NetResult Comm::FramedRingExchange(const char* send_buf, size_t send_n,
                                   char* recv_buf, size_t recv_n) {
  size_t soff = 0, roff = 0;
  while (soff < send_n || roff < recv_n) {
    int out_li = soff < send_n ? ring_next_ : -1;
    int in_li = roff < recv_n ? ring_prev_ : -1;
    size_t sk = out_li >= 0 ? std::min(kFrameChunk, send_n - soff) : 0;
    size_t rk = in_li >= 0 ? std::min(kFrameChunk, recv_n - roff) : 0;
    NetResult r = FramedStep(out_li, send_buf + soff, sk,
                             in_li, recv_buf + roff, rk);
    if (r != NetResult::kOk) return r;
    soff += sk;
    roff += rk;
  }
  return NetResult::kOk;
}

// In-place repair of one dead link. The side that originally dialed
// redials (UDS token first, then TCP, linear backoff) while the side
// that originally accepted re-accepts on its persistent listener; both
// re-run the rank handshake, then exchange recv_seq so the frame layer
// can tell whether its in-flight frame was delivered. All blocking
// reads are bounded — a half-open peer costs at most the redial
// budget, after which the caller escalates to full ReconnectLinks.
bool Comm::ResurrectLink(int li) {
  Link& l = links_[li];
  l.conn.Close();
  const double deadline = GetTime() + resurrect_ms_ / 1000.0;
  TcpConn c;
  if (l.i_connect) {
    for (int attempt = 0; GetTime() < deadline; ++attempt) {
      c = TcpConn();
      if (cfg_.GetBool("rabit_local_uds", true) && !l.peer_token.empty())
        c = TcpConn::ConnectLocal(l.peer_token);
      if (!c.ok()) {
        try {
          c = TcpConn::Connect(l.peer_host, l.peer_port, /*retries=*/0);
        } catch (const Error&) {
          c = TcpConn();
        }
      }
      if (c.ok() && LinkHandshake(&c, rank_, l.peer_rank) == Handshake::kOk)
        break;
      c.Close();
      int ms = std::min(100 * (attempt + 1), 1000);
      std::this_thread::sleep_for(std::chrono::milliseconds(ms));
    }
    if (!c.ok()) return false;
  } else {
    for (;;) {
      int remain = static_cast<int>((deadline - GetTime()) * 1000.0);
      if (remain <= 0) return false;
      TcpConn a = listener_.AcceptTimeout(std::min(remain, 500));
      if (!a.ok()) continue;  // timeout slice; loop re-checks the budget
      uint32_t magic = 0, prank = 0;
      if (!a.RecvAllTimeout(&magic, 4, 2000) || magic != kLinkMagic)
        continue;  // stray connect: drop without consuming the budget
      if (!a.RecvAllTimeout(&prank, 4, 2000) ||
          static_cast<int>(prank) != l.peer_rank)
        continue;
      try {
        a.SendU32(static_cast<uint32_t>(rank_));
      } catch (const Error&) {
        continue;
      }
      c = std::move(a);
      break;
    }
  }
  // both-send-first is safe on a fresh stream: 4 bytes fit any socket
  // buffer, so neither side can block the other's send
  try {
    c.SendU32(l.recv_seq);
  } catch (const Error&) {
    return false;
  }
  uint32_t peer_recv = 0;
  if (!c.RecvAllTimeout(&peer_recv, 4, resurrect_ms_)) return false;
  l.peer_recv_seq = peer_recv;
  c.SetKeepAlive();
  c.SetNonBlocking(true);
  l.conn = std::move(c);
  ++stat_link_resurrects_;
  if (debug_) {
    LogInfo(StrFormat("rank %d resurrected link to rank %d", rank_,
                      l.peer_rank));
  }
  return true;
}

// Framed tree allreduce: stop-and-wait per segment — receive each
// child's segment whole (verified), fold, pass up, receive the result,
// fan down. Unlike the streaming variant, segment size must be derived
// only from values every rank shares (elem_size + the compile-time
// chunk), never from the local child count — receiver and sender must
// compute identical frame sequences.
NetResult Comm::TryAllreduceTreeFramed(char* buf, size_t elem_size,
                                       size_t count, ReduceFn reducer) {
  const size_t total = elem_size * count;
  std::vector<int> children;
  int parent_link = -1;
  for (size_t i = 0; i < tree_idx_.size(); ++i) {
    if (static_cast<int>(i) == parent_pos_) parent_link = tree_idx_[i];
    else children.push_back(tree_idx_[i]);
  }
  const size_t seg_max =
      std::max<size_t>(kFrameChunk / elem_size, 1) * elem_size;
  std::vector<char> cbuf(std::min<size_t>(seg_max, total));
  for (size_t seg_off = 0; seg_off < total; seg_off += seg_max) {
    const size_t S = std::min(seg_max, total - seg_off);
    char* base = buf + seg_off;
    for (int c : children) {
      NetResult r = FramedRecvLink(c, cbuf.data(), S);
      if (r != NetResult::kOk) return r;
      reducer(base, cbuf.data(), S / elem_size);
    }
    if (parent_link >= 0) {
      NetResult r = FramedSendLink(parent_link, base, S);
      if (r != NetResult::kOk) return r;
      r = FramedRecvLink(parent_link, base, S);
      if (r != NetResult::kOk) return r;
    }
    for (int c : children) {
      NetResult r = FramedSendLink(c, base, S);
      if (r != NetResult::kOk) return r;
    }
  }
  return NetResult::kOk;
}

// Framed targeted multicast: same deterministic binary-tree plan as
// TryRouteData, with chunk-level store-and-forward (receive a verified
// frame, then relay it) instead of byte streaming — a corrupt chunk is
// stopped at the first hop, never propagated down the routing subtree.
NetResult Comm::TryRouteDataFramed(char* buf, size_t size, int src_rank,
                                   const std::vector<uint8_t>& need) {
  if (world_ == 1 || size == 0) return NetResult::kOk;
  const int P = world_;
  bool any = false;
  for (int r = 0; r < P; ++r) any = any || (need[r] != 0);
  if (!any) return NetResult::kOk;
  std::vector<int> toward(P, -1), order;
  std::vector<uint8_t> seen(P, 0), sub(P, 0);
  order.reserve(P);
  order.push_back(src_rank);
  seen[src_rank] = 1;
  for (size_t i = 0; i < order.size(); ++i) {
    int u = order[i];
    int nb[3] = {u > 0 ? (u - 1) / 2 : -1, 2 * u + 1, 2 * u + 2};
    for (int v : nb) {
      if (v < 0 || v >= P || seen[v]) continue;
      seen[v] = 1;
      toward[v] = u;
      order.push_back(v);
    }
  }
  for (size_t i = order.size(); i-- > 0;) {
    int u = order[i];
    if (need[u]) sub[u] = 1;
    if (sub[u] && toward[u] >= 0) sub[toward[u]] = 1;
  }
  const bool is_src = (rank_ == src_rank);
  if (!is_src && !sub[rank_]) return NetResult::kOk;
  auto link_of = [&](int peer) {
    for (size_t i = 0; i < links_.size(); ++i)
      if (links_[i].peer_rank == peer) return static_cast<int>(i);
    Fail(StrFormat("route peer %d not among links", peer));
    return -1;
  };
  int in_link = is_src ? -1 : link_of(toward[rank_]);
  std::vector<int> out_links;
  int my_nb[3] = {rank_ > 0 ? (rank_ - 1) / 2 : -1, 2 * rank_ + 1,
                  2 * rank_ + 2};
  for (int v : my_nb) {
    if (v < 0 || v >= P || toward[v] != rank_) continue;
    if (sub[v]) out_links.push_back(link_of(v));
  }
  std::vector<char> scratch;
  char* data = buf;
  if (!is_src && !need[rank_]) {
    scratch.resize(size);
    data = scratch.data();
  }
  for (size_t off = 0; off < size; off += kFrameChunk) {
    size_t k = std::min(kFrameChunk, size - off);
    if (in_link >= 0) {
      NetResult r = FramedRecvLink(in_link, data + off, k);
      if (r != NetResult::kOk) return r;
    }
    for (int ol : out_links) {
      NetResult r = FramedSendLink(ol, data + off, k);
      if (r != NetResult::kOk) return r;
    }
  }
  return NetResult::kOk;
}

// ---------------------------------------------------------------------------
// Singleton
// ---------------------------------------------------------------------------

static std::unique_ptr<Comm>& CommSlot() {
  // per-thread engine store (reference ThreadLocalStore + EngineThreadLocal,
  // engine.cc:33-43): each thread owns an independent engine slot; the
  // engine itself remains documented not-thread-safe.
  thread_local std::unique_ptr<Comm> slot;
  return slot;
}

Comm* GetComm() {
  if (CommSlot() == nullptr) {
    // Pre-Init fallback (reference engine.cc:74-85): an un-initialized
    // base engine so rank-0/world-1 topology queries — and world-1 no-op
    // collectives — work before Init, matching the reference's static
    // AllreduceBase default manager.
    thread_local Comm fallback;
    return &fallback;
  }
  return CommSlot().get();
}

Comm* NewCommFromEnv(int argc, const char* const* argv);  // factory, capi.cc

void InitComm(int argc, const char* const* argv) {
  if (CommSlot() != nullptr) return;
  CommSlot().reset(NewCommFromEnv(argc, argv));
  CommSlot()->Init(argc, argv);
}

void FinalizeComm() {
  if (CommSlot() != nullptr) {
    CommSlot()->Shutdown();
    CommSlot().reset();
  }
}

}  // namespace rt
