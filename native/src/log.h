// Logging + invariant checks for the rabit_tpu native core.
// Capability parity with reference include/rabit/internal/utils.h
// (Assert/Check/Error with configurable die-vs-throw, utils.h:65-95),
// redesigned around C++ exceptions: the engine throws rt::Error unless
// RABIT_STOP_PROCESS_ON_ERROR is set, in which case it exits(-1) like
// the reference default.
#ifndef RT_LOG_H_
#define RT_LOG_H_

#include <cstdarg>
#include <cstdio>
#include <cstdlib>
#include <ctime>
#include <stdexcept>
#include <string>

namespace rt {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& msg) : std::runtime_error(msg) {}
};

// Set from config rabit_stop_process_on_error /
// DMLC_WORKER_STOP_PROCESS_ON_ERROR (reference allreduce_base.cc:202-210).
inline bool& StopProcessOnError() {
  static bool v = false;
  return v;
}

inline std::string StrFormat(const char* fmt, ...) {
  va_list args;
  va_start(args, fmt);
  char buf[1024];
  vsnprintf(buf, sizeof(buf), fmt, args);
  va_end(args);
  return std::string(buf);
}

[[noreturn]] inline void Fail(const std::string& msg) {
  if (StopProcessOnError()) {
    fprintf(stderr, "[rabit_tpu] fatal: %s\n", msg.c_str());
    fflush(stderr);
    exit(-1);
  }
  throw Error(msg);
}

inline void LogInfo(const std::string& msg) {
  fprintf(stderr, "[rabit_tpu] %s\n", msg.c_str());
  fflush(stderr);
}

// Monotonic wall clock in seconds (reference utils::GetTime, timer.h:21-38).
inline double GetTime() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<double>(ts.tv_sec) + 1e-9 * ts.tv_nsec;
}

}  // namespace rt

#define RT_CHECK(cond, msg)                                              \
  do {                                                                   \
    if (!(cond)) {                                                       \
      ::rt::Fail(::rt::StrFormat("check failed %s:%d: %s", __FILE__,     \
                                 __LINE__, std::string(msg).c_str()));   \
    }                                                                    \
  } while (0)

#endif  // RT_LOG_H_
