// key=value configuration, mirroring the reference's parameter system
// (env vars then argv overrides, allreduce_base.cc:42-68 + SetParam
// chains; size suffixes .cc:156-176).
#ifndef RT_CONFIG_H_
#define RT_CONFIG_H_

#include <cstdlib>
#include <map>
#include <string>
#include <vector>

#include "log.h"

namespace rt {

class Config {
 public:
  static std::string Normalize(std::string k) {
    for (auto& c : k) c = static_cast<char>(tolower(c));
    if (k.rfind("dmlc_", 0) == 0) k = "rabit_" + k.substr(5);
    return k;
  }

  void Set(const std::string& key, const std::string& val) {
    std::string k = Normalize(key);
    if (k == "rabit_mock" || k == "mock") {
      repeated_[k].push_back(val);
    } else {
      values_[k] = val;
    }
  }

  void LoadEnv() {
    static const char* kEnv[] = {
        "DMLC_TASK_ID", "DMLC_NUM_ATTEMPT", "DMLC_TRACKER_URI",
        "DMLC_TRACKER_PORT", "DMLC_WORKER_STOP_PROCESS_ON_ERROR",
        "DMLC_WORKER_CONNECT_RETRY",
        "RABIT_TASK_ID", "RABIT_TRACKER_URI", "RABIT_TRACKER_PORT",
        "RABIT_CONNECT_RETRY", "rabit_connect_retry",
        "RABIT_NUM_TRIAL", "RABIT_BOOTSTRAP_CACHE", "RABIT_DEBUG",
        "RABIT_WORLD_SIZE", "rabit_world_size",
        "RABIT_REDUCE_RING_MINCOUNT", "rabit_reduce_ring_mincount",
        "RABIT_REDUCE_BUFFER", "rabit_reduce_buffer",
        "RABIT_GLOBAL_REPLICA", "rabit_global_replica",
        "RABIT_LOCAL_REPLICA", "rabit_local_replica"};
    for (const char* name : kEnv) {
      const char* v = getenv(name);
      if (v != nullptr) Set(name, v);
    }
  }

  // Hadoop-streaming autodetect (reference allreduce_base.cc:70-104):
  // inside a Hadoop task, mapred_tip_id names the logical task (stable
  // across restarts -> task id) and mapred_task_id ends in the attempt
  // counter ("attempt_<job>_m_000003_4" -> trial 4). Explicit DMLC/RABIT
  // settings win, so call this LAST — after both LoadEnv and LoadArgs.
  void LoadHadoopEnv() {
    const char* tip = getenv("mapred_tip_id");
    if (tip == nullptr) tip = getenv("mapreduce_task_id");
    if (tip != nullptr && Get("rabit_task_id").empty()) {
      Set("rabit_task_id", tip);
    }
    const char* att = getenv("mapred_task_id");
    if (att == nullptr) att = getenv("mapreduce_task_attempt_id");
    // DMLC_NUM_ATTEMPT normalizes to rabit_num_attempt; either explicit
    // form must win over the Hadoop-derived value
    if (att != nullptr && Get("rabit_num_trial").empty() &&
        Get("rabit_num_attempt").empty()) {
      std::string s(att);
      auto us = s.rfind('_');
      if (us != std::string::npos && us + 1 < s.size()) {
        Set("rabit_num_trial", s.substr(us + 1));
      }
    }
  }

  void LoadArgs(int argc, const char* const* argv) {
    for (int i = 0; i < argc; ++i) {
      std::string a(argv[i]);
      auto eq = a.find('=');
      if (eq != std::string::npos) Set(a.substr(0, eq), a.substr(eq + 1));
    }
  }

  std::string Get(const std::string& key, const std::string& dflt = "") const {
    auto it = values_.find(Normalize(key));
    return it == values_.end() ? dflt : it->second;
  }

  long GetInt(const std::string& key, long dflt = 0) const {
    std::string v = Get(key);
    return v.empty() ? dflt : atol(v.c_str());
  }

  bool GetBool(const std::string& key, bool dflt = false) const {
    std::string v = Get(key);
    if (v.empty()) return dflt;
    return v == "1" || v == "true" || v == "yes" || v == "on";
  }

  // "256MB" / "1G" / "1024" -> bytes
  size_t GetSize(const std::string& key, size_t dflt = 0) const {
    std::string v = Get(key);
    if (v.empty()) return dflt;
    char* end = nullptr;
    double x = strtod(v.c_str(), &end);
    std::string suffix(end);
    for (auto& c : suffix) c = static_cast<char>(toupper(c));
    size_t mult = 1;
    if (suffix == "K" || suffix == "KB") mult = 1ull << 10;
    else if (suffix == "M" || suffix == "MB") mult = 1ull << 20;
    else if (suffix == "G" || suffix == "GB") mult = 1ull << 30;
    else if (suffix == "B" || suffix.empty()) mult = 1;
    else Fail("bad size suffix: " + v);
    return static_cast<size_t>(x * mult);
  }

  std::vector<std::string> GetRepeated(const std::string& key) const {
    std::vector<std::string> out;
    auto it = repeated_.find(Normalize(key));
    if (it != repeated_.end()) out = it->second;
    return out;
  }

 private:
  std::map<std::string, std::string> values_;
  std::map<std::string, std::vector<std::string>> repeated_;
};

}  // namespace rt

#endif  // RT_CONFIG_H_
