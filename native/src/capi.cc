// C ABI implementation (reference src/c_api.cc). All entry points catch
// rt::Error and surface it through RbtGetLastError (return -1), so the
// ctypes binding can raise Python exceptions instead of aborting the
// interpreter.
#include "../include/rabit_tpu_c.h"

#include <cstring>
#include <string>

#include "comm.h"
#include "engine_mpi.h"
#include "mock.h"
#include "robust.h"

namespace rt {

// engine-variant factory: rabit_engine=base|robust|mock (the reference
// selects at link time via librabit/_base/_mock; we select at runtime)
Comm* NewCommFromEnv(int argc, const char* const* argv) {
  Config cfg;
  cfg.LoadEnv();
  cfg.LoadArgs(argc, argv);
  std::string variant = cfg.Get("rabit_engine", "robust");
  if (!cfg.GetRepeated("mock").empty() ||
      !cfg.GetRepeated("rabit_mock").empty()) {
    variant = "mock";
  }
  if (variant == "base" || variant == "native") return new Comm();
  if (variant == "mock") return new MockComm();
  if (variant == "mpi") {
#ifdef RT_WITH_MPI
    return new MpiComm();
#else
    rt::Fail("rabit_engine=mpi but this build has no MPI "
             "(configure with an MPI toolchain to enable it)");
#endif
  }
  return new RobustComm();
}

static std::string& LastError() {
  // thread_local to match the per-thread engine slot: two threads
  // driving their own engines must not clobber each other's error
  // (and the error of the thread that failed is the one its caller
  // will fetch via RbtGetLastError)
  static thread_local std::string err;
  return err;
}

}  // namespace rt

using rt::GetComm;

#define RT_API_BEGIN() try {
#define RT_API_END()                         \
  }                                          \
  catch (const std::exception& e) {          \
    rt::LastError() = e.what();              \
    return -1;                               \
  }                                          \
  return 0;

extern "C" {

const char* RbtGetLastError(void) { return rt::LastError().c_str(); }

int RbtInit(int argc, const char** argv) {
  RT_API_BEGIN();
  rt::InitComm(argc, argv);
  RT_API_END();
}

int RbtInitAfterException(void) {
  RT_API_BEGIN();
  GetComm()->InitAfterException();
  RT_API_END();
}

int RbtResize(const char* cmd) {
  RT_API_BEGIN();
  GetComm()->Resize(cmd && cmd[0] ? cmd : "recover");
  RT_API_END();
}

int RbtFinalize(void) {
  RT_API_BEGIN();
  rt::FinalizeComm();
  RT_API_END();
}

int RbtGetRank(void) {
  try {
    return GetComm()->rank();
  } catch (const std::exception& e) {
    rt::LastError() = e.what();
    return -1;
  }
}

int RbtGetWorldSize(void) {
  try {
    return GetComm()->world_size();
  } catch (const std::exception& e) {
    rt::LastError() = e.what();
    return -1;
  }
}

int RbtIsDistributed(void) {
  try {
    return GetComm()->is_distributed() ? 1 : 0;
  } catch (const std::exception& e) {
    rt::LastError() = e.what();
    return -1;
  }
}

int RbtTrackerPrint(const char* msg) {
  RT_API_BEGIN();
  GetComm()->TrackerPrint(msg ? msg : "");
  RT_API_END();
}

// copy s into (buf, max_len) always NUL-terminated; *len reports the
// full untruncated length so callers can detect truncation
static void CopyCStr(const std::string& s, char* buf, size_t* len,
                     size_t max_len) {
  if (max_len > 0) {
    size_t n = s.size() < max_len - 1 ? s.size() : max_len - 1;
    memcpy(buf, s.data(), n);
    buf[n] = '\0';
  }
  *len = s.size();
}

int RbtGetProcessorName(char* buf, size_t* len, size_t max_len) {
  RT_API_BEGIN();
  CopyCStr(GetComm()->host(), buf, len, max_len);
  RT_API_END();
}

int RbtAllreduceEx(void* sendrecvbuf, size_t count, int dtype, int op,
                   void (*prepare_fun)(void*), void* prepare_arg,
                   const char* cache_key) {
  RT_API_BEGIN();
  rt::ReduceFn fn = rt::GetReducer(op, dtype);
  GetComm()->Allreduce(sendrecvbuf, rt::DTypeSize(dtype), count, fn,
                       prepare_fun, prepare_arg, cache_key ? cache_key : "",
                       dtype, op);
  RT_API_END();
}

int RbtSetDataPlane(RbtDataPlaneFn fn, void* ctx, uint64_t min_bytes) {
  RT_API_BEGIN();
  GetComm()->SetDataPlane(fn, ctx, static_cast<size_t>(min_bytes));
  RT_API_END();
}

int RbtWorldEpoch(void) {
  try {
    return static_cast<int>(GetComm()->world_epoch());
  } catch (const std::exception& e) {
    rt::LastError() = e.what();
    return -1;
  }
}

int RbtCoordAddr(char* buf, size_t* len, size_t max_len) {
  RT_API_BEGIN();
  std::string addr = GetComm()->coord_host() + ":" +
                     std::to_string(GetComm()->coord_port());
  CopyCStr(addr, buf, len, max_len);
  RT_API_END();
}

int RbtAllreduce(void* sendrecvbuf, size_t count, int dtype, int op,
                 void (*prepare_fun)(void*), void* prepare_arg) {
  return RbtAllreduceEx(sendrecvbuf, count, dtype, op, prepare_fun,
                        prepare_arg, "");
}

// trampoline context for custom reducers: the engine's ReduceFn carries
// no user pointer, so stash (fn, ctx) for the duration of the call.
// thread_local, not global: the engine slot is per-thread, so the
// trampoline always runs on the thread that stashed the pair — globals
// here would let a second thread's Allreduce swap the reducer out from
// under the first (matching the reference's static-buffer C ABI,
// c_api.cc:219-245, which was documented single-threaded instead).
static thread_local RbtReduceFn g_custom_red = nullptr;
static thread_local void* g_custom_ctx = nullptr;

static void CustomReduceTrampoline(void* dst, const void* src, size_t n) {
  g_custom_red(dst, src, n, g_custom_ctx);
}

int RbtAllreduceRaw(void* sendrecvbuf, size_t elem_size, size_t count,
                    RbtReduceFn red, void* red_ctx,
                    void (*prepare_fun)(void*), void* prepare_arg,
                    const char* cache_key) {
  RT_API_BEGIN();
  g_custom_red = red;
  g_custom_ctx = red_ctx;
  GetComm()->Allreduce(sendrecvbuf, elem_size, count, CustomReduceTrampoline,
                       prepare_fun, prepare_arg,
                       cache_key ? cache_key : "");
  g_custom_red = nullptr;
  g_custom_ctx = nullptr;
  RT_API_END();
}

int RbtBroadcastEx(void* sendrecvbuf, uint64_t size, int root,
                   const char* cache_key) {
  RT_API_BEGIN();
  GetComm()->Broadcast(sendrecvbuf, static_cast<size_t>(size), root,
                       cache_key ? cache_key : "");
  RT_API_END();
}

int RbtBroadcast(void* sendrecvbuf, uint64_t size, int root) {
  return RbtBroadcastEx(sendrecvbuf, size, root, "");
}

// static buffers keep checkpoints alive across the ABI (reference
// c_api.cc:219-245). thread_local so each engine thread's checkpoint
// survives until ITS next load, independent of other threads.
static thread_local std::string g_load_global, g_load_local;

int RbtLoadCheckpoint(const char** out_global, uint64_t* out_global_len,
                      const char** out_local, uint64_t* out_local_len) {
  try {
    int version = GetComm()->LoadCheckpoint(
        &g_load_global, out_local ? &g_load_local : nullptr);
    if (out_global) {
      *out_global = g_load_global.data();
      *out_global_len = g_load_global.size();
    }
    if (out_local) {
      *out_local = g_load_local.data();
      *out_local_len = g_load_local.size();
    }
    return version;
  } catch (const std::exception& e) {
    rt::LastError() = e.what();
    return -1;
  }
}

int RbtCheckpoint(const char* global, uint64_t global_len, const char* local,
                  uint64_t local_len) {
  RT_API_BEGIN();
  GetComm()->Checkpoint(std::string(global ? global : "", global_len),
                        std::string(local ? local : "", local_len));
  RT_API_END();
}

int RbtLazyCheckpoint(const char* global, uint64_t global_len) {
  RT_API_BEGIN();
  // thread_local: the engine keeps a pointer to this buffer until the
  // next checkpoint, and the engine slot itself is per-thread
  static thread_local std::string lazy_buf;
  lazy_buf.assign(global ? global : "", global_len);
  GetComm()->LazyCheckpoint(&lazy_buf);
  RT_API_END();
}

int RbtVersionNumber(void) {
  try {
    return GetComm()->version_number();
  } catch (const std::exception& e) {
    rt::LastError() = e.what();
    return -1;
  }
}

int RbtInterrupt(void) {
  // no RT_API_BEGIN: just an atomic flag raise, and it must stay
  // safe from the watchdog monitor thread while the engine thread is
  // blocked inside a collective
  rt::RequestInterrupt("interrupt");
  return 0;
}

int RbtInterruptEx(const char* reason) {
  // reason-tagged raise (watchdog rungs pass their escalation name so
  // recovery logs can attribute the reset); same thread-safety
  // contract as RbtInterrupt
  rt::RequestInterrupt(reason ? reason : "interrupt");
  return 0;
}

const char* RbtInterruptReason(void) {
  // thread_local snapshot buffer: the returned pointer stays valid on
  // the calling thread until its next RbtInterruptReason call, even if
  // another thread raises a new interrupt meanwhile
  static thread_local std::string snap;
  snap = rt::LastInterruptReason();
  return snap.c_str();
}

int RbtRecoveryStats(uint64_t* retries, uint64_t* frame_rejects,
                     uint64_t* resurrects) {
  RT_API_BEGIN();
  GetComm()->GetRecoveryStats(retries, frame_rejects, resurrects);
  RT_API_END();
}

uint32_t RbtFrameCrc32(const void* buf, uint64_t len) {
  return rt::Crc32(buf, static_cast<size_t>(len));
}

// no-op link anchor (reference RabitLinkTag, c_api.h:156-164)
int RbtLinkTag(void) { return 0; }

}  // extern "C"
