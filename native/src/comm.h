// Comm — the socket collective engine (CPU fallback + control plane).
//
// Capability parity with the reference's AllreduceBase
// (src/allreduce_base.{h,cc}): tracker rendezvous, tree/ring link
// topology, poll()-driven streaming tree allreduce with simultaneous
// up-reduce/down-broadcast (.cc:475-640), dynamic-in-link tree broadcast
// (.cc:649-737), ring reduce-scatter/all-gather/allreduce (.cc:751-949).
// Fresh design differences:
//  - the ring-vs-tree crossover (reduce_ring_mincount) is actually
//    dispatched (the reference documents it but hardwires tree,
//    SURVEY §2 #3);
//  - our own tracker protocol (the reference's tracker lives in
//    dmlc-core, outside its repo): binary, length-prefixed, with the
//    tracker barrier guaranteeing all peers are listening before link
//    wiring begins, so connect/accept needs no retry loop;
//  - errors surface as NetResult codes returned up through Try*;
//    the robust subclass turns them into recovery, the base engine
//    fails fast.
//
// Thread model (rt_thread_annotations.h): one Comm per thread — the C
// ABI resolves a thread_local engine slot (comm.cc GetComm), so every
// member below is engine-thread state needing no lock. The ONLY
// cross-thread channels into a running collective are the interrupt
// plane (net.h RequestInterrupt: atomic flag + mutex-guarded reason)
// and the process-global tracker env; anything else shared across
// threads must carry an rt::Mutex and RT_GUARDED_BY annotations so
// clang's -Wthread-safety (and TSan, RT_SANITIZE=thread) can check it.
#ifndef RT_COMM_H_
#define RT_COMM_H_

#include <memory>
#include <string>
#include <vector>

#include "config.h"
#include "net.h"
#include "reducer.h"

namespace rt {

// --- framed-plane wire format (rabit_frame_crc=1) ------------------------
// A frame is [FrameHeader][scales_len sidecar bytes][len payload bytes];
// crc covers sidecar + payload as one stream. The wire-metadata fields
// make a frame self-describing for EQuARX-style block quantization
// (parallel/wire.py): codec names the payload encoding, block_log2 the
// elements per shipped f32 scale, scales_len the sidecar size in bytes.
// Unquantized frames (the only kind the native plane currently sends)
// carry codec=0 / block_log2=0 / scales_len=0 and are parsed by the
// same state machine — the metadata costs 8 header bytes per frame
// (vs the 1 MiB payload cap) and buys hop-local retransmission of
// quantized hops without a second frame round for the sidecar.
enum FrameWireCodec : uint8_t {
  kFrameWireNone = 0,   // payload is raw elements
  kFrameWireBf16 = 1,   // payload is bf16-cast elements, no sidecar
  kFrameWireInt8 = 2,   // payload is int8 blocks + f32 max-abs scales
};

struct FrameHeader {
  uint32_t magic = 0;
  uint32_t seq = 0;
  uint32_t len = 0;         // payload bytes (EXCLUDING the sidecar)
  uint32_t crc = 0;         // over sidecar then payload, one stream
  uint8_t wire_codec = 0;   // FrameWireCodec
  uint8_t block_log2 = 0;   // int8 scaling-block elements = 1 << this
  uint16_t reserved = 0;
  uint32_t scales_len = 0;  // sidecar bytes (f32 scales; 0 unless int8)
};
static_assert(sizeof(FrameHeader) == 24, "frame header is wire format");

// Out-of-band description of a quantized payload a sender attaches to
// one FramedStep: the sidecar buffer is NOT part of sbuf — the framed
// plane interleaves it on the wire and checksums both together.
struct FrameWireMeta {
  uint8_t codec = kFrameWireNone;
  uint8_t block_log2 = 0;
  const char* scales = nullptr;
  uint32_t scales_len = 0;
};

class Comm {
 public:
  virtual ~Comm();

  virtual void Init(int argc, const char* const* argv);
  virtual void Shutdown();

  // In-process world resize (elastic membership): tear down and
  // re-form the ring/tree link topology from a fresh tracker
  // assignment WITHOUT process exit — the native mirror of what
  // epoch_reset(world) does for the Python modules. cmd is "recover"
  // (a survivor re-forming after an eviction; the tracker treats it as
  // re-registration of a known rank) or "join" (a previously evicted
  // rank parking until the next epoch boundary re-admits it). rank_,
  // world_ and world_epoch_ all come back reassigned; the robust
  // subclass additionally resets its world-sized recovery state.
  virtual void Resize(const char* cmd = "recover");

  int rank() const { return rank_; }
  int world_size() const { return world_; }
  virtual bool is_distributed() const { return tracker_uri_ != ""; }
  const std::string& host() const { return host_; }

  // In-process reset after the caller caught an exception mid-collective
  // (reference IEngine::InitAfterException, allreduce_robust.h:163-169):
  // drop any half-streamed link state so the next collective starts
  // clean. Only the robust engine can honor it.
  virtual void InitAfterException() {
    Fail("InitAfterException requires the robust engine");
  }

  // Lazy data-prep hook (reference prepare_fun, engine.h:74-96): invoked
  // right before the reduction executes, skipped when the robust engine
  // replays a cached result.
  typedef void (*PrepareFn)(void*);

  // Pluggable accelerator data plane: when registered, payload
  // reductions with known (dtype, op) semantics and nbytes >=
  // dataplane_minbytes_ execute through this callback (the XLA
  // device-mesh collective) instead of the socket tree/ring; the socket
  // path remains the control plane (consensus, replay, checkpoints) and
  // the sub-threshold path — the host/device crossover SURVEY §7 calls
  // out for small-message latency. ``epoch`` is the tracker's link
  // (re)registration epoch: it advances exactly when the worker set was
  // rewired, telling the callback to tear down and re-form its
  // fixed-membership device world (XLA collectives cannot survive a
  // membership change; the reference's socket substrate can,
  // allreduce_robust.cc:602-613). Returns 0 on success; nonzero is
  // treated like a link failure and enters recovery.
  typedef int (*DataPlaneFn)(void* buf, uint64_t count, int dtype, int op,
                             uint32_t epoch, void* ctx);
  void SetDataPlane(DataPlaneFn fn, void* ctx, size_t min_bytes) {
    dataplane_ = fn;
    dataplane_ctx_ = ctx;
    dataplane_minbytes_ = min_bytes;
  }
  uint32_t world_epoch() const { return world_epoch_; }
  const std::string& coord_host() const { return coord_host_; }
  int coord_port() const { return coord_port_; }

  // In-place elementwise allreduce (IEngine::Allreduce, engine.h:74-96).
  // ``dtype``/``op`` are the C-ABI enum codes when known (runtime
  // dispatch, capi.cc) or -1 for opaque custom reducers — only coded ops
  // are eligible for the accelerator data plane.
  virtual void Allreduce(void* buf, size_t elem_size, size_t count,
                         ReduceFn reducer, PrepareFn prepare = nullptr,
                         void* prepare_arg = nullptr,
                         const char* cache_key = "",
                         int dtype = -1, int op = -1);
  // Broadcast size bytes from root into buf everywhere
  // (IEngine::Broadcast, engine.h:98-105).
  virtual void Broadcast(void* buf, size_t size, int root,
                         const char* cache_key = "");
  virtual void TrackerPrint(const std::string& msg);

  // Checkpoint API: functional in the robust subclass; the base engine
  // only tracks the version counter (like the reference's MPI engine,
  // engine_mpi.cc:47-60).
  virtual int LoadCheckpoint(std::string* global, std::string* local);
  virtual void Checkpoint(const std::string& global,
                          const std::string& local);
  virtual void LazyCheckpoint(const std::string* global);
  int version_number() const { return version_; }

  // Recovery provenance counters (self-healing data plane): drained by
  // the Python engine after each collective into telemetry rows.
  // Engine-thread only, like every accessor here — the Python binding
  // calls it from the thread that owns this Comm's thread_local slot.
  void GetRecoveryStats(uint64_t* retries, uint64_t* frame_rejects,
                        uint64_t* resurrects) const {
    if (retries) *retries = stat_retries_;
    if (frame_rejects) *frame_rejects = stat_frame_rejects_;
    if (resurrects) *resurrects = stat_link_resurrects_;
  }

 protected:
  struct Link {
    TcpConn conn;
    int peer_rank = -1;
    // Resurrection metadata: how this link was originally wired, so a
    // mid-collective conn death can be repaired in place (connector
    // re-dials, acceptor re-accepts) without tearing the whole world
    // down through ReconnectLinks.
    std::string peer_host;
    std::string peer_token;   // UDS fast-path token, may be empty
    int peer_port = 0;
    bool i_connect = false;   // true: this side dialed; false: accepted
    // Framed-mode stop-and-wait sequence state, per direction. seqs
    // reset naturally on ReconnectLinks (fresh Link structs all ranks).
    uint32_t send_seq = 0;    // next frame seq to send
    uint32_t recv_seq = 0;    // next frame seq expected
    uint32_t peer_recv_seq = 0;  // peer's recv_seq learned at resurrection
  };

  // --- bootstrap -------------------------------------------------------
  void SetupFromConfig(const Config& cfg);
  // Connect tracker, send cmd, receive topology, wire peer links.
  // cmd is "start" or "recover" (reference ReConnectLinks,
  // allreduce_base.cc:264-441).
  void ReconnectLinks(const char* cmd);
  TcpConn ConnectTrackerCmd(const std::string& cmd);
  void CloseLinks();

  // --- collectives (return NetResult for the recovery layer) ----------
  // Dispatch one payload reduction: accelerator data plane when
  // eligible (hook set, coded op, above crossover), else socket
  // tree/ring. The single execute point the robust engine wraps — the
  // role of the reference's virtual TryAllreduce dispatch
  // (allreduce_robust.cc:159-219 wrapping allreduce_base.cc:457-463).
  NetResult ExecuteAllreduce(void* buf, size_t elem_size, size_t count,
                             ReduceFn reducer, int dtype, int op);
  NetResult TryAllreduce(void* buf, size_t elem_size, size_t count,
                         ReduceFn reducer);
  NetResult TryAllreduceTree(char* buf, size_t elem_size, size_t count,
                             ReduceFn reducer);
  NetResult TryAllreduceRing(char* buf, size_t elem_size, size_t count,
                             ReduceFn reducer);
  NetResult TryReduceScatterRing(char* buf, size_t elem_size, size_t count,
                                 ReduceFn reducer);
  NetResult TryAllgatherRing(char* buf, size_t elem_size, size_t count);
  NetResult TryBroadcast(char* buf, size_t size, int root);
  // Targeted single-source multicast for recovery routing: stream
  // ``size`` bytes from ``src_rank`` to exactly the ranks with
  // ``need[r] != 0``, along complete-binary-tree paths (the tracker's
  // topology is parent=(r-1)/2, so every rank derives the full tree
  // locally). Ranks on no src->requester path return immediately —
  // recovery traffic is O(data x routing-subtree), not O(data x world)
  // (the capability of the reference's MsgPassing/TryRecoverData
  // routing, allreduce_robust-inl.h:33-166, allreduce_robust.cc:749-861,
  // built on plan-from-consensus instead of hop-by-hop passes).
  NetResult TryRouteData(char* buf, size_t size, int src_rank,
                         const std::vector<uint8_t>& need);

  // full-duplex fixed-size exchange with ring neighbors
  NetResult RingExchange(const char* send_buf, size_t send_n,
                         char* recv_buf, size_t recv_n);

  // --- framed data plane (rabit_frame_crc=1) ---------------------------
  // CRC-framed stop-and-wait variants of the streaming collectives: every
  // payload hop is a [magic|seq|len|crc|wire-meta] frame answered by an
  // ACK/NAK verdict, so a corrupt frame is rejected and retransmitted
  // hop-local — never accumulated into the reduction. Off by default;
  // with the knob unset none of this code runs and the wire is
  // byte-identical.
  // One duplex frame round on up to two links: send a frame out out_li
  // (if >= 0) while receiving one from in_li (if >= 0), then exchange
  // verdicts; retransmits CRC-rejected directions up to frame_retries_.
  // ``wm`` describes an optionally block-quantized payload (codec +
  // block + f32 scale sidecar, see FrameWireMeta below): the sidecar
  // rides INSIDE the frame, covered by the same CRC, so a corrupt
  // scale retransmits hop-local exactly like corrupt payload bytes.
  // ``rscales`` receives the inbound sidecar (required non-null to
  // accept a quantized frame — a receiver not expecting quantization
  // treats one as plan skew and resets).
  NetResult FramedStep(int out_li, const char* sbuf, size_t sn,
                       int in_li, char* rbuf, size_t rn,
                       const FrameWireMeta* wm = nullptr,
                       std::vector<char>* rscales = nullptr);
  NetResult FramedSendLink(int li, const char* buf, size_t n);
  NetResult FramedRecvLink(int li, char* buf, size_t n);
  NetResult FramedRingExchange(const char* send_buf, size_t send_n,
                               char* recv_buf, size_t recv_n);
  NetResult TryAllreduceTreeFramed(char* buf, size_t elem_size,
                                   size_t count, ReduceFn reducer);
  NetResult TryRouteDataFramed(char* buf, size_t size, int src_rank,
                               const std::vector<uint8_t>& need);
  // In-place repair of one dead link: connector re-dials (UDS token
  // first, then TCP, bounded backoff within resurrect_ms_), acceptor
  // re-accepts with the same budget; both re-handshake rank identity
  // and exchange recv_seq so an in-flight frame is not double-applied.
  // Returns false when the budget is exhausted — caller escalates to
  // the full ReconnectLinks ladder via kReset.
  bool ResurrectLink(int li);

  // --- state -----------------------------------------------------------
  Config cfg_;
  int rank_ = 0;
  int world_ = 1;
  int version_ = 0;
  std::string host_;
  std::string task_id_;
  int num_attempt_ = 0;
  std::string tracker_uri_;
  int tracker_port_ = 9091;
  size_t ring_mincount_ = 32 << 10;   // reference default 32K elements
  bool ring_user_set_ = false;        // crossover set explicitly?
  // tracker-announced "whole world is on one host" (shared medium: the
  // ring's 2(p-1) serialized phases lose to the streaming tree, so the
  // crossover DEFAULT prefers tree there — measured up to ~1.6x at
  // 16 MB, world 8, loaded single host). Tracker-computed so every rank
  // decides identically.
  bool all_local_peers_ = false;
  size_t reduce_buffer_ = 256u << 20; // reference default 256MB
  bool debug_ = false;
  // advertise at tracker registration that a data plane will be
  // registered post-Init (rabit_dataplane config), so the tracker hosts
  // a device-world coordinator on demand
  bool dataplane_intent_ = false;
  // Hadoop-streaming reporter:status heartbeat (reference ReportStatus,
  // allreduce_base.h:215-220), emitted each recovery round
  bool report_status_ = false;
  void ReportStatus(const char* phase, uint32_t seq = 0) const;

  // accelerator data plane (see SetDataPlane)
  DataPlaneFn dataplane_ = nullptr;
  void* dataplane_ctx_ = nullptr;
  size_t dataplane_minbytes_ = 0;
  // link (re)registration epoch + per-epoch device-world coordinator
  // (rank 0's host and a fresh port), assigned by the tracker
  uint32_t world_epoch_ = 0;
  std::string coord_host_;
  int coord_port_ = 0;

  // self-healing data plane knobs + provenance counters.
  // Engine-thread only (per-thread Comm slot); deliberately NOT
  // atomic/locked — the watchdog monitor thread reaches a collective
  // exclusively through net.h RequestInterrupt, never through these.
  bool frame_crc_ = false;      // rabit_frame_crc: CRC-framed payloads
  int frame_retries_ = 4;       // rabit_frame_retries: per-hop re-rounds
  int resurrect_ms_ = 5000;     // rabit_resurrect_ms: redial budget
  uint64_t stat_retries_ = 0;          // robust-layer round re-executions
  uint64_t stat_frame_rejects_ = 0;    // CRC-rejected frames (hop-local)
  uint64_t stat_link_resurrects_ = 0;  // links repaired in place

  Listener listener_;
  // One socket per distinct neighbor (tree parent/children and ring
  // prev/next may overlap; collectives run sequentially so links are
  // shared, like the reference's single link array).
  std::vector<Link> links_;
  std::vector<int> tree_idx_;   // indices into links_: parent + children
  int parent_pos_ = -1;         // position of parent within tree_idx_, -1=root
  int ring_prev_ = -1;          // index into links_
  int ring_next_ = -1;          // index into links_
  bool links_up_ = false;

  // byte offsets splitting count elements into world_ contiguous ranges:
  // world_+1 entries, elem-aligned
  std::vector<size_t> RingRanges(size_t count, size_t elem_size) const;
};

// Singleton management (reference engine.cc thread-local; our engine is
// process-global since the API is documented single-threaded).
Comm* GetComm();
void InitComm(int argc, const char* const* argv);
void FinalizeComm();

}  // namespace rt

#endif  // RT_COMM_H_
