// MockComm — scripted fault injection for recovery testing.
// Capability parity with the reference AllreduceMock
// (src/allreduce_mock.h): repeated ``mock=rank,version,seqno,ntrial``
// parameters script a process kill (exit 255) at exactly that engine
// call, with ntrial fed from the tracker's restart-attempt counter so
// each respawn advances the schedule (allreduce_mock.h:34-44,149-181).
#ifndef RT_MOCK_H_
#define RT_MOCK_H_

#include <cstdio>
#include <cstdlib>
#include <set>
#include <tuple>

#include "robust.h"

namespace rt {

class MockComm : public RobustComm {
 public:
  void Init(int argc, const char* const* argv) override {
    RobustComm::Init(argc, argv);
    auto entries = cfg_.GetRepeated("rabit_mock");
    auto more = cfg_.GetRepeated("mock");
    entries.insert(entries.end(), more.begin(), more.end());
    for (const auto& e : entries) {
      int r = -1, v = -1, s = -1, t = -1;
      if (sscanf(e.c_str(), "%d,%d,%d,%d", &r, &v, &s, &t) == 4) {
        kill_points_.insert(std::make_tuple(r, v, s, t));
      } else {
        Fail("bad mock entry (want rank,version,seqno,ntrial): " + e);
      }
    }
  }

 protected:
  void OnEngineCall(const char* fn) override {
    auto key = std::make_tuple(rank_, version_,
                               static_cast<int>(seq_counter_), num_attempt_);
    if (kill_points_.count(key)) {
      fprintf(stderr,
              "[mock] rank %d killing itself at %s "
              "(version=%d seq=%u trial=%d)\n",
              rank_, fn, version_, seq_counter_, num_attempt_);
      fflush(stderr);
      exit(255);
    }
  }

 private:
  std::set<std::tuple<int, int, int, int>> kill_points_;
};

}  // namespace rt

#endif  // RT_MOCK_H_
