// MockComm — scripted fault injection for recovery testing.
// Capability parity with the reference AllreduceMock
// (src/allreduce_mock.h): repeated ``mock=rank,version,seqno,ntrial``
// parameters script a process kill (exit 255) at exactly that engine
// call, with ntrial fed from the tracker's restart-attempt counter so
// each respawn advances the schedule (allreduce_mock.h:34-44,149-181).
// Also carries the reference mock's two test adapters:
//  - report_stats=1: per-version checkpoint sizes + cumulative
//    allreduce/broadcast seconds, printed to the tracker at each
//    checkpoint (allreduce_mock.h:95-103);
//  - force_local=1: reroutes a global-only checkpoint through the
//    local-checkpoint ring path, so global-only test programs exercise
//    local replication/healing (the role of the reference's
//    DummySerializer/ComboSerializer, allreduce_mock.h:73-92,122-147 —
//    our engine checkpoints opaque strings, so the payload simply rides
//    the local slot and is handed back as the global model on load).
#ifndef RT_MOCK_H_
#define RT_MOCK_H_

#include <cstdio>
#include <cstdlib>
#include <set>
#include <tuple>

#include "robust.h"

namespace rt {

class MockComm : public RobustComm {
 public:
  void Init(int argc, const char* const* argv) override {
    RobustComm::Init(argc, argv);
    auto entries = cfg_.GetRepeated("rabit_mock");
    auto more = cfg_.GetRepeated("mock");
    entries.insert(entries.end(), more.begin(), more.end());
    for (const auto& e : entries) {
      int r = -1, v = -1, s = -1, t = -1;
      if (sscanf(e.c_str(), "%d,%d,%d,%d", &r, &v, &s, &t) == 4) {
        kill_points_.insert(std::make_tuple(r, v, s, t));
      } else {
        Fail("bad mock entry (want rank,version,seqno,ntrial): " + e);
      }
    }
    report_stats_ = cfg_.GetBool("report_stats", false) ||
                    cfg_.GetBool("rabit_report_stats", false);
    force_local_ = cfg_.GetBool("force_local", false) ||
                   cfg_.GetBool("rabit_force_local", false);
  }

  void Allreduce(void* buf, size_t elem_size, size_t count, ReduceFn reducer,
                 PrepareFn prepare = nullptr, void* prepare_arg = nullptr,
                 const char* cache_key = "",
                 int dtype = -1, int op = -1) override {
    double t0 = GetTime();
    RobustComm::Allreduce(buf, elem_size, count, reducer, prepare,
                          prepare_arg, cache_key, dtype, op);
    collective_seconds_ += GetTime() - t0;
  }

  void Broadcast(void* buf, size_t size, int root,
                 const char* cache_key = "") override {
    double t0 = GetTime();
    RobustComm::Broadcast(buf, size, root, cache_key);
    collective_seconds_ += GetTime() - t0;
  }

  void Checkpoint(const std::string& global, const std::string& local)
      override {
    if (force_local_) {
      RT_CHECK(local.empty(),
               "force_local expects a global-only checkpoint to reroute");
      RobustComm::Checkpoint("", global);
    } else {
      RobustComm::Checkpoint(global, local);
    }
    if (report_stats_) {
      TrackerPrint(StrFormat(
          "[mock] rank %d version %d: global %zu B, local %zu B, "
          "collectives %.6f s", rank_, version_number(), global.size(),
          local.size(), collective_seconds_));
    }
  }

  int LoadCheckpoint(std::string* global, std::string* local) override {
    if (!force_local_) return RobustComm::LoadCheckpoint(global, local);
    std::string g, l;
    int version = RobustComm::LoadCheckpoint(&g, &l);
    if (global) *global = l;  // payload rode the local slot
    if (local) local->clear();
    return version;
  }

 protected:
  void OnEngineCall(const char* fn) override {
    auto key = std::make_tuple(rank_, version_,
                               static_cast<int>(seq_counter_), num_attempt_);
    if (kill_points_.count(key)) {
      fprintf(stderr,
              "[mock] rank %d killing itself at %s "
              "(version=%d seq=%u trial=%d)\n",
              rank_, fn, version_, seq_counter_, num_attempt_);
      fflush(stderr);
      exit(255);
    }
  }

 private:
  std::set<std::tuple<int, int, int, int>> kill_points_;
  bool report_stats_ = false;
  bool force_local_ = false;
  double collective_seconds_ = 0.0;
};

}  // namespace rt

#endif  // RT_MOCK_H_
