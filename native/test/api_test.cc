// Single-process unit tests for the public C++ header API
// (native/include/rabit_tpu/rabit.h) — the role of the reference's
// test/cpp gtest tier, written as a plain asserting executable so no
// test framework dependency is needed.
#include <rabit_tpu/rabit.h>

// Release builds define NDEBUG, which no-ops CHECK(); tests must
// always check.
#define CHECK(cond)                                              \
  do {                                                           \
    if (!(cond)) {                                               \
      std::fprintf(stderr, "CHECK failed %s:%d: %s\n", __FILE__, \
                   __LINE__, #cond);                             \
      std::exit(1);                                              \
    }                                                            \
  } while (0)

#include <cmath>
#include <cstdlib>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

static int g_prepare_calls = 0;

struct Model : public rabit::Serializable {
  std::vector<double> w;
  void Load(rabit::Stream* fi) override {
    uint64_t n = 0;
    fi->Read(&n, sizeof(n));
    w.resize(n);
    if (n) fi->Read(w.data(), n * sizeof(double));
  }
  void Save(rabit::Stream* fo) const override {
    uint64_t n = w.size();
    fo->Write(&n, sizeof(n));
    if (n) fo->Write(w.data(), n * sizeof(double));
  }
};

struct Pair {
  int a, b;
};
static void ReducePair(Pair& d, const Pair& s) {
  d.a += s.a;
  if (s.b > d.b) d.b = s.b;
}

struct Blob : public rabit::Serializable {
  int x = 0;
  void Load(rabit::Stream* fi) override { fi->Read(&x, sizeof(x)); }
  void Save(rabit::Stream* fo) const override { fo->Write(&x, sizeof(x)); }
  void Reduce(const Blob& src, size_t) { x += src.x; }
};

static void TestStreams() {
  std::string buf;
  rabit::MemoryBufferStream ms(&buf);
  double pi = 3.14159;
  ms.Write(&pi, sizeof(pi));
  ms.Write("abc", 3);
  ms.Seek(0);
  double back = 0;
  CHECK(ms.Read(&back, sizeof(back)) == sizeof(back));
  CHECK(back == pi);
  char s[4] = {0};
  CHECK(ms.Read(s, 3) == 3 && std::memcmp(s, "abc", 3) == 0);
  CHECK(ms.Read(s, 3) == 0);  // exhausted

  char region[16];
  rabit::MemoryFixSizeBuffer fb(region, sizeof(region));
  int v = 42;
  fb.Write(&v, sizeof(v));
  fb.Seek(0);
  int got = 0;
  fb.Read(&got, sizeof(got));
  CHECK(got == 42);
  std::printf("streams ok\n");
}

static void TestSingleNodeCollectives() {
  // world 1: collectives are identity but prepare_fun must still run
  // (reference engine_empty.cc:23-133 contract)
  std::vector<float> x(4, 0.f);
  rabit::Allreduce<rabit::op::Sum>(x.data(), x.size(), [&]() {
    ++g_prepare_calls;
    for (auto& v : x) v = 7.f;
  });
  CHECK(g_prepare_calls == 1);
  CHECK(x[0] == 7.f);

  std::string msg = "solo";
  rabit::Broadcast(&msg, 0);
  CHECK(msg == "solo");

  std::vector<int32_t> vec{1, 2, 3};
  rabit::Broadcast(&vec, 0);
  CHECK(vec.size() == 3 && vec[2] == 3);
  std::printf("single-node collectives ok\n");
}

static void TestCheckpointRoundtrip() {
  Model m;
  CHECK(rabit::LoadCheckPoint(&m) == 0);
  m.w = {1.0, 2.5, -3.0};
  rabit::CheckPoint(&m);
  CHECK(rabit::VersionNumber() == 1);

  Model m2;
  int version = rabit::LoadCheckPoint(&m2);
  CHECK(version == 1);
  CHECK(m2.w.size() == 3 && m2.w[1] == 2.5);

  m2.w.push_back(9.0);
  rabit::LazyCheckPoint(&m2);
  CHECK(rabit::VersionNumber() == 2);
  Model m3;
  CHECK(rabit::LoadCheckPoint(&m3) == 2);
  CHECK(m3.w.size() == 4 && m3.w[3] == 9.0);
  std::printf("checkpoint roundtrip ok\n");
}

static void TestCustomReducers() {
  rabit::Reducer<Pair, ReducePair> red;
  std::vector<Pair> p(2);
  p[0] = {3, 5};
  p[1] = {-1, 0};
  red.Allreduce(p.data(), p.size());
  CHECK(p[0].a == 3 && p[0].b == 5);  // world 1: unchanged

  rabit::SerializeReducer<Blob> sred;
  std::vector<Blob> blobs(2);
  blobs[0].x = 11;
  blobs[1].x = 22;
  sred.Allreduce(blobs.data(), sizeof(int), blobs.size());
  CHECK(blobs[0].x == 11 && blobs[1].x == 22);  // world 1 roundtrip
  std::printf("custom reducers ok\n");
}

int main(int argc, char* argv[]) {
  // pre-Init topology queries hit the rank-0/world-1 fallback engine
  // (reference engine.cc:74-85: GetEngine returns a static
  // un-initialized manager before Init)
  CHECK(rabit::GetRank() == 0);
  CHECK(rabit::GetWorldSize() == 1);
  CHECK(!rabit::IsDistributed());
  CHECK(rabit::VersionNumber() == 0);

  rabit::Init(argc, argv);
  CHECK(rabit::GetRank() == 0);
  CHECK(rabit::GetWorldSize() == 1);
  CHECK(!rabit::IsDistributed());
  CHECK(!rabit::GetProcessorName().empty());
  rabit::TrackerPrintf("api_test rank %d of %d\n", rabit::GetRank(),
                       rabit::GetWorldSize());
  CHECK(RbtLinkTag() == 0);

  TestStreams();
  TestSingleNodeCollectives();
  TestCheckpointRoundtrip();
  TestCustomReducers();

  // per-thread engine store (reference ThreadLocalStore/EngineThreadLocal,
  // engine.cc:33-43): another thread owns an INDEPENDENT slot — it sees
  // the pre-Init fallback (version 0), not this thread's engine, and can
  // run its own isolated world-1 lifecycle without touching ours
  Model marker;
  const int base_version = rabit::VersionNumber();
  rabit::CheckPoint(&marker);
  CHECK(rabit::VersionNumber() == base_version + 1);
  bool thread_ok = false;
  std::thread([&thread_ok] {
    bool ok = rabit::GetRank() == 0 && rabit::GetWorldSize() == 1 &&
              rabit::VersionNumber() == 0;  // NOT the main thread's 1
    rabit::Init(0, nullptr);
    float v[2] = {2.0f, 3.0f};
    rabit::Allreduce<rabit::op::Sum>(v, 2);  // world-1 no-op, must work
    ok = ok && v[0] == 2.0f && rabit::VersionNumber() == 0;
    rabit::Finalize();
    thread_ok = ok;
  }).join();
  CHECK(thread_ok);
  CHECK(rabit::VersionNumber() == base_version + 1);  // ours untouched
  std::printf("thread-local engine store ok\n");

  rabit::Finalize();
  std::printf("api_test: all ok\n");
  return 0;
}
