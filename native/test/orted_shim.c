/* orted reconstructed from libopen-rte (the Debian runtime package
   ships the library but not the binary): the real orted's main() is a
   one-line call to orte_daemon(). */
extern int orte_daemon(int argc, char *argv[]);
int main(int argc, char *argv[]) { return orte_daemon(argc, argv); }
