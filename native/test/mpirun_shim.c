/* mpirun reconstructed from libopen-rte, like orted_shim.c (the Debian
   runtime package ships the library but no launcher binaries).

   OpenMPI 4.1's real mpirun main() delegates to orterun(), whose whole
   machinery is EXPORTED from libopen-rte: orte_submit_init parses the
   mpirun command line and brings up the HNP, orte_submit_job launches
   the app procs and fires launch/complete callbacks, and the caller
   spins the opal event base meanwhile (Debian links the system
   libevent, so the loop is plain event_base_loop). One non-obvious
   piece recovered from the upstream 4.1.x orterun.c: the HNP must
   register orte_daemon_recv on the daemon-command RML tag itself —
   the app-launch xcast lands there, and without the listener the local
   procs are never forked. */
#include <stdbool.h>
#include <stddef.h>
#include <stdint.h>

struct event_base;
extern struct event_base *orte_event_base;
extern volatile unsigned char orte_event_base_active; /* opal bool */
extern int orte_exit_status;

/* orte_process_name_t: {jobid u32, vpid u32} */
typedef struct { uint32_t jobid; uint32_t vpid; } orte_process_name_t;
extern orte_process_name_t orte_name_wildcard;

#define ORTE_RML_TAG_DAEMON 1
#define ORTE_RML_PERSISTENT true

typedef void (*orte_submit_cbfunc_t)(int index, void *jdata, int ret,
                                     void *cbdata);

extern int orte_submit_init(int argc, char *argv[], void *opts);
extern int orte_submit_job(char *cmd[], int *index,
                           orte_submit_cbfunc_t launch_cb,
                           void *launch_cbdata,
                           orte_submit_cbfunc_t complete_cb,
                           void *complete_cbdata);
extern int orte_submit_finalize(void);
extern int orte_finalize(void);

/* the real RML buffer-receive callback signature (orte/mca/rml/rml.h):
   (status, peer, buffer, tag, cbdata) — declared exactly so the
   registration below is well-defined C, not an ABI-coincidence cast */
struct opal_buffer_t;
typedef void (*orte_rml_buffer_callback_fn_t)(int status,
                                              orte_process_name_t *peer,
                                              struct opal_buffer_t *buffer,
                                              uint32_t tag, void *cbdata);
extern void orte_rml_API_recv_buffer_nb(orte_process_name_t *peer,
                                        uint32_t tag, bool persistent,
                                        orte_rml_buffer_callback_fn_t cb,
                                        void *cbdata);
extern void orte_daemon_recv(int status, orte_process_name_t *sender,
                             struct opal_buffer_t *buffer, uint32_t tag,
                             void *cbdata);
extern int event_base_loop(struct event_base *, int);
#define EVLOOP_ONCE 0x01

static volatile bool launch_active = true;
static volatile bool complete_active = true;

static void launched(int index, void *jdata, int ret, void *cbdata)
{
    (void)index; (void)jdata; (void)cbdata;
    if (ret != 0)
        orte_exit_status = ret;
    launch_active = false;
}

static void completed(int index, void *jdata, int ret, void *cbdata)
{
    (void)index; (void)jdata; (void)ret; (void)cbdata;
    complete_active = false;
}

int main(int argc, char *argv[])
{
    int idx = 0;
    int rc = orte_submit_init(argc, argv, NULL);
    if (rc != 0)
        return 1;
    /* listen for daemon commands sent to the HNP itself (see header) */
    orte_rml_API_recv_buffer_nb(&orte_name_wildcard, ORTE_RML_TAG_DAEMON,
                                ORTE_RML_PERSISTENT, orte_daemon_recv, NULL);
    rc = orte_submit_job(argv, &idx, launched, NULL, completed, NULL);
    if (rc != 0)
        return 1;
    while (orte_event_base_active && launch_active)
        event_base_loop(orte_event_base, EVLOOP_ONCE);
    while (orte_event_base_active && complete_active)
        event_base_loop(orte_event_base, EVLOOP_ONCE);
    orte_submit_finalize();
    orte_finalize();
    return orte_exit_status;
}
