// Single-process self-test of the native core: config parsing, reducers,
// streams, and the C ABI in world-1 mode. Multi-process behavior is
// exercised by the Python integration tests through the tracker.
#undef NDEBUG  // asserts are the test
#include <cassert>
#include <cstdio>
#include <cstring>
#include <vector>

#include "../include/rabit_tpu_c.h"
#include "../src/comm.h"
#include "../src/config.h"
#include "../src/reducer.h"
#include "../src/stream.h"

static void TestConfig() {
  rt::Config cfg;
  cfg.Set("DMLC_TASK_ID", "t7");
  assert(cfg.Get("rabit_task_id") == "t7");
  cfg.Set("rabit_reduce_buffer", "256MB");
  assert(cfg.GetSize("rabit_reduce_buffer") == (256ull << 20));
  cfg.Set("x", "1G");
  assert(cfg.GetSize("x") == (1ull << 30));
  cfg.Set("rabit_debug", "1");
  assert(cfg.GetBool("rabit_debug"));
  cfg.Set("mock", "0,0,0,0");
  cfg.Set("mock", "1,1,1,0");
  assert(cfg.GetRepeated("mock").size() == 2);
  printf("config ok\n");
}

static void TestReducers() {
  float a[3] = {1, 5, 3}, b[3] = {4, 2, 6};
  rt::GetReducer(rt::kSum, rt::kFloat32)(a, b, 3);
  assert(a[0] == 5 && a[1] == 7 && a[2] == 9);
  uint32_t c[2] = {0b0011, 0b0101}, d[2] = {0b0110, 0b1000};
  rt::GetReducer(rt::kBitOR, rt::kUInt32)(c, d, 2);
  assert(c[0] == 0b0111 && c[1] == 0b1101);
  int64_t e[2] = {1, 9}, f[2] = {7, 2};
  rt::GetReducer(rt::kMax, rt::kInt64)(e, f, 2);
  assert(e[0] == 7 && e[1] == 9);
  bool threw = false;
  try {
    rt::GetReducer(rt::kBitOR, rt::kFloat32);
  } catch (const rt::Error&) {
    threw = true;
  }
  assert(threw);  // BitOR on float rejected (reference c_api.cc:26-35)
  printf("reducers ok\n");
}

static void TestStream() {
  rt::MemStream s;
  s.WritePod<int>(42);
  s.WriteStr("hello");
  s.Seek(0);
  assert(s.ReadPod<int>() == 42);
  assert(s.ReadStr() == "hello");
  printf("stream ok\n");
}

static void TestFrameWire() {
  // framed-plane wire format: the header layout is a cross-version
  // contract (sizeof asserted in comm.h) and defaults must describe an
  // unquantized frame — a pre-quantization peer's zero-filled metadata
  // parses as codec none / no sidecar
  rt::FrameHeader h;
  assert(sizeof(h) == 24);
  assert(h.wire_codec == rt::kFrameWireNone);
  assert(h.block_log2 == 0 && h.scales_len == 0);
  // the frame CRC covers scale sidecar + payload as ONE stream: the
  // incremental form over the two regions must equal the one-shot CRC
  // over their concatenation (and both must match RbtFrameCrc32, the
  // ABI surface Python cross-checks against zlib.crc32)
  const char scales[] = "\x00\x00\x80\x3f\x00\x00\x00\x40";  // 2 f32
  const char payload[] = "quantized-blocks";
  std::vector<char> cat(scales, scales + 8);
  cat.insert(cat.end(), payload, payload + sizeof(payload));
  uint32_t inc = rt::Crc32Begin();
  inc = rt::Crc32Feed(inc, scales, 8);
  inc = rt::Crc32Feed(inc, payload, sizeof(payload));
  assert(rt::Crc32End(inc) == rt::Crc32(cat.data(), cat.size()));
  assert(rt::Crc32(cat.data(), cat.size()) ==
         RbtFrameCrc32(cat.data(), cat.size()));
  // a sender's metadata block round-trips through the header fields
  rt::FrameWireMeta wm;
  wm.codec = rt::kFrameWireInt8;
  wm.block_log2 = 10;  // 1024-element scaling blocks
  wm.scales = scales;
  wm.scales_len = 8;
  h.wire_codec = wm.codec;
  h.block_log2 = wm.block_log2;
  h.scales_len = wm.scales_len;
  assert((1u << h.block_log2) == 1024u && h.scales_len == 8);
  printf("frame wire ok\n");
}

static void TestCApiWorld1() {
  const char* argv[] = {"rabit_debug=0"};
  assert(RbtInit(1, argv) == 0);
  assert(RbtGetRank() == 0);
  assert(RbtGetWorldSize() == 1);
  assert(RbtIsDistributed() == 0);
  std::vector<int> buf = {1, 2, 3};
  assert(RbtAllreduce(buf.data(), buf.size(), 2 /*int32*/, 2 /*sum*/,
                      nullptr, nullptr) == 0);
  assert(buf[0] == 1 && buf[2] == 3);  // identity at world 1
  const char* msg = "model-v1";
  assert(RbtCheckpoint(msg, strlen(msg), nullptr, 0) == 0);
  assert(RbtVersionNumber() == 1);
  const char* g = nullptr;
  uint64_t glen = 0;
  int version = RbtLoadCheckpoint(&g, &glen, nullptr, nullptr);
  assert(version == 1);
  assert(glen == strlen(msg) && memcmp(g, msg, glen) == 0);
  assert(RbtFinalize() == 0);
  printf("c-api world-1 ok\n");
}

int main() {
  TestConfig();
  TestReducers();
  TestStream();
  TestFrameWire();
  TestCApiWorld1();
  printf("rt_selftest: ALL OK\n");
  return 0;
}
