// Self-verifying MPI-engine test (the reference proves its MPI engine
// by running the same self-checking programs against it as against the
// socket engine, test/Makefile:60-62). Runs at whatever world size the
// launcher provides: every collective's expected value is computed
// analytically from (rank, world), so the same binary passes as an
// OpenMPI singleton (world=1, the only launch mode on this image — no
// mpirun) and under any real MPI launcher.
#include <cstdio>
#include <cstring>
#include <vector>

#define RT_WITH_MPI 1
#include "../src/engine_mpi.h"
#include "../src/log.h"

static void SumF32(void* dst, const void* src, size_t n) {
  auto* d = static_cast<float*>(dst);
  auto* s = static_cast<const float*>(src);
  for (size_t i = 0; i < n; ++i) d[i] += s[i];
}

int main(int argc, char** argv) {
  rt::MpiComm comm;
  comm.Init(argc, argv);
  const int rank = comm.rank();
  const int world = comm.world_size();

  // allreduce SUM: every rank contributes rank+i
  const size_t n = 64;
  std::vector<float> buf(n);
  for (size_t i = 0; i < n; ++i) buf[i] = static_cast<float>(rank + i);
  bool prepared = false;
  comm.Allreduce(buf.data(), sizeof(float), n, SumF32,
                 [](void* arg) { *static_cast<bool*>(arg) = true; },
                 &prepared);
  RT_CHECK(prepared, "prepare_fun must run");
  for (size_t i = 0; i < n; ++i) {
    float want = 0;
    for (int r = 0; r < world; ++r) want += static_cast<float>(r + i);
    RT_CHECK(buf[i] == want, "allreduce SUM wrong");
  }

  // broadcast from root 0
  char msg[16] = {0};
  if (rank == 0) snprintf(msg, sizeof(msg), "mpi-ok");
  comm.Broadcast(msg, sizeof(msg), 0);
  RT_CHECK(strcmp(msg, "mpi-ok") == 0, "broadcast wrong");

  // checkpoint API: version-only no-ops (reference engine_mpi.cc:47-60)
  comm.Checkpoint("g", "l");
  RT_CHECK(comm.version_number() == 1, "version must bump");
  std::string g, l;
  RT_CHECK(comm.LoadCheckpoint(&g, &l) == 0 && g.empty(),
           "MPI engine checkpoints must be empty no-ops");

  // Direct MPI-level ABI checks: the engine's world==1 fast path skips
  // the MPI calls, so exercise the shim's handle/type/op declarations
  // against the real library explicitly (valid MPI at any world size).
  MPI_Datatype pair;
  RT_CHECK(MPI_Type_contiguous(8, MPI_BYTE, &pair) == MPI_SUCCESS,
           "MPI_Type_contiguous failed");
  RT_CHECK(MPI_Type_commit(&pair) == MPI_SUCCESS, "commit failed");
  MPI_Op op;
  rt::mpi_detail::Ctx().fn = SumF32;
  RT_CHECK(MPI_Op_create(rt::mpi_detail::Trampoline, 1, &op) == MPI_SUCCESS,
           "MPI_Op_create failed");
  double two[2] = {1.5 * (rank + 1), -2.5};
  RT_CHECK(MPI_Allreduce(MPI_IN_PLACE, two, 2, pair, op,
                         MPI_COMM_WORLD) == MPI_SUCCESS,
           "MPI_Allreduce failed");
  RT_CHECK(MPI_Op_free(&op) == MPI_SUCCESS, "op free failed");
  RT_CHECK(MPI_Type_free(&pair) == MPI_SUCCESS, "type free failed");
  int chk = 41;
  RT_CHECK(MPI_Bcast(&chk, 4, MPI_BYTE, 0, MPI_COMM_WORLD) == MPI_SUCCESS,
           "MPI_Bcast failed");
  RT_CHECK(chk == 41, "bcast corrupted data");

  comm.TrackerPrint("mpi_engine_test: all ok");
  comm.Shutdown();
  if (rank == 0) printf("mpi_engine_test: world=%d all ok\n", world);
  return 0;
}
