// Collective-performance harness — the role of the reference's
// test/speed_test.cc: time Allreduce(Sum/Max) and Broadcast over nrep
// repetitions, allreduce the per-rank timings to report cluster
// mean/min/max and effective MB/s.
//
// Usage (under the tracker):
//   python -m rabit_tpu.tracker.launch -n 4 ./speed_test ndata=100000 nrep=20
#include <rabit_tpu/rabit.h>

#include <chrono>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

static double Now() {
  using namespace std::chrono;
  return duration<double>(steady_clock::now().time_since_epoch()).count();
}

struct Timing {
  double sum_s = 0, min_s = 1e30, max_s = 0;
  void Add(double s) {
    sum_s += s;
    if (s < min_s) min_s = s;
    if (s > max_s) max_s = s;
  }
};

static void Report(const char* name, Timing t, int nrep, size_t nbytes) {
  // cluster-wide stats ride the same engine being measured
  double stats[3] = {t.sum_s, t.min_s, -t.max_s};
  rabit::Allreduce<rabit::op::Sum>(&stats[0], 1);
  rabit::Allreduce<rabit::op::Min>(&stats[1], 1);
  rabit::Allreduce<rabit::op::Min>(&stats[2], 1);
  if (rabit::GetRank() == 0) {
    double mean = stats[0] / (nrep * rabit::GetWorldSize());
    double mbs = nbytes / mean / 1e6;
    std::printf("%-12s mean %.6fs  min %.6fs  max %.6fs  %.1f MB/s\n",
                name, mean, stats[1], -stats[2], mbs);
  }
}

int main(int argc, char* argv[]) {
  rabit::Init(argc, argv);
  size_t ndata = 100000;
  int nrep = 20;
  for (int i = 1; i < argc; ++i) {
    unsigned long v = 0;
    if (std::sscanf(argv[i], "ndata=%lu", &v) == 1) ndata = v;
    if (std::sscanf(argv[i], "nrep=%lu", &v) == 1) nrep = int(v);
  }
  const int rank = rabit::GetRank();
  const size_t nbytes = ndata * sizeof(float);
  std::vector<float> buf(ndata);

  Timing t_sum, t_max, t_bcast;
  for (int r = 0; r < nrep; ++r) {
    for (size_t i = 0; i < ndata; ++i) buf[i] = float(rank + r + i % 17);
    double t0 = Now();
    rabit::Allreduce<rabit::op::Sum>(buf.data(), ndata);
    t_sum.Add(Now() - t0);

    for (size_t i = 0; i < ndata; ++i) buf[i] = float(rank * (r + 1));
    t0 = Now();
    rabit::Allreduce<rabit::op::Max>(buf.data(), ndata);
    t_max.Add(Now() - t0);

    t0 = Now();
    rabit::Broadcast(buf.data(), nbytes, r % rabit::GetWorldSize());
    t_bcast.Add(Now() - t0);
  }

  if (rank == 0) {
    std::printf("== speed_test: %zu floats (%zu bytes) x %d reps, "
                "world=%d ==\n",
                ndata, nbytes, nrep, rabit::GetWorldSize());
  }
  Report("allreduce.sum", t_sum, nrep, nbytes);
  Report("allreduce.max", t_max, nrep, nbytes);
  Report("broadcast", t_bcast, nrep, nbytes);
  rabit::Finalize();
  return 0;
}
