/* Clang thread-safety (capability) annotation macros + annotated lock
 * wrappers for the native engine. Under clang, `-Wthread-safety` turns
 * these into static lock-discipline checks (the C++ twin of the Python
 * analyzer's C001/C002 — see doc/static_analysis.md); under gcc and
 * every other compiler they expand to nothing, so annotated code
 * compiles identically everywhere.
 *
 * Conventions mirror tools/analysis/locks.py:
 *   - shared state is tagged RT_GUARDED_BY(mu)    (Python: # guarded-by: _mu)
 *   - helpers that assume the lock use RT_REQUIRES (Python: *_locked suffix)
 *   - lock-order constraints use RT_ACQUIRED_BEFORE/AFTER (Python: C002)
 *
 * The engine is per-thread (one Comm per thread slot, comm.cc); state
 * that is "engine-thread only" rather than mutex-guarded is tagged with
 * the kEngineThread ThreadRole capability instead of a real lock.
 */
#ifndef RT_THREAD_ANNOTATIONS_H_
#define RT_THREAD_ANNOTATIONS_H_

#if defined(__clang__) && defined(__has_attribute)
#define RT_HAS_TSA_(x) __has_attribute(x)
#else
#define RT_HAS_TSA_(x) 0
#endif

#if RT_HAS_TSA_(capability)
#define RT_TSA_(x) __attribute__((x))
#else
#define RT_TSA_(x)
#endif

#define RT_CAPABILITY(x) RT_TSA_(capability(x))
#define RT_SCOPED_CAPABILITY RT_TSA_(scoped_lockable)
#define RT_GUARDED_BY(x) RT_TSA_(guarded_by(x))
#define RT_PT_GUARDED_BY(x) RT_TSA_(pt_guarded_by(x))
#define RT_ACQUIRED_BEFORE(...) RT_TSA_(acquired_before(__VA_ARGS__))
#define RT_ACQUIRED_AFTER(...) RT_TSA_(acquired_after(__VA_ARGS__))
#define RT_REQUIRES(...) RT_TSA_(requires_capability(__VA_ARGS__))
#define RT_REQUIRES_SHARED(...) \
  RT_TSA_(requires_shared_capability(__VA_ARGS__))
#define RT_ACQUIRE(...) RT_TSA_(acquire_capability(__VA_ARGS__))
#define RT_ACQUIRE_SHARED(...) RT_TSA_(acquire_shared_capability(__VA_ARGS__))
#define RT_RELEASE(...) RT_TSA_(release_capability(__VA_ARGS__))
#define RT_TRY_ACQUIRE(...) RT_TSA_(try_acquire_capability(__VA_ARGS__))
#define RT_EXCLUDES(...) RT_TSA_(locks_excluded(__VA_ARGS__))
#define RT_ASSERT_CAPABILITY(x) RT_TSA_(assert_capability(x))
#define RT_RETURN_CAPABILITY(x) RT_TSA_(lock_returned(x))
#define RT_NO_THREAD_SAFETY_ANALYSIS RT_TSA_(no_thread_safety_analysis)

#ifdef __cplusplus
#include <mutex>

namespace rt {

// std::mutex with the capability attribute attached, so members can be
// RT_GUARDED_BY it and functions can RT_REQUIRES/RT_EXCLUDES it.
class RT_CAPABILITY("mutex") Mutex {
 public:
  Mutex() = default;
  Mutex(const Mutex&) = delete;
  Mutex& operator=(const Mutex&) = delete;
  void lock() RT_ACQUIRE() { mu_.lock(); }
  void unlock() RT_RELEASE() { mu_.unlock(); }
  bool try_lock() RT_TRY_ACQUIRE(true) { return mu_.try_lock(); }

 private:
  std::mutex mu_;
};

// RAII guard the analysis understands (std::lock_guard<rt::Mutex>
// would also check, but this keeps call sites annotation-free).
class RT_SCOPED_CAPABILITY LockGuard {
 public:
  explicit LockGuard(Mutex& mu) RT_ACQUIRE(mu) : mu_(mu) { mu_.lock(); }
  ~LockGuard() RT_RELEASE() { mu_.unlock(); }
  LockGuard(const LockGuard&) = delete;
  LockGuard& operator=(const LockGuard&) = delete;

 private:
  Mutex& mu_;
};

// Role capability: not a lock but a statically-checked claim that the
// caller runs on a particular thread. Engine state that is per-thread
// by design (the thread-local Comm slot) is RT_GUARDED_BY(kEngineThread);
// entry points assert the role once via ThreadRoleScope so the analysis
// rejects any path that touches engine state from a monitor thread.
class RT_CAPABILITY("role") ThreadRole {};

class RT_SCOPED_CAPABILITY ThreadRoleScope {
 public:
  explicit ThreadRoleScope(ThreadRole& role) RT_ACQUIRE(role)
      : role_(role) {}
  ~ThreadRoleScope() RT_RELEASE() {}
  ThreadRoleScope(const ThreadRoleScope&) = delete;
  ThreadRoleScope& operator=(const ThreadRoleScope&) = delete;

 private:
  ThreadRole& role_;
};

}  // namespace rt
#endif  // __cplusplus

#endif  // RT_THREAD_ANNOTATIONS_H_
