/*
 * rabit_tpu flat C ABI — capability parity with the reference
 * include/rabit/c_api.h (c_api.h:37-164): init/finalize, rank/world
 * queries, tracker print, in-place allreduce with runtime op x dtype
 * dispatch, broadcast, pickle-friendly checkpoint wrappers. Fresh
 * additions: an explicit cache-key argument so bindings can keep
 * caller-signature replay keys (the reference loses them across its C
 * ABI), and an engine-variant selector.
 */
#ifndef RABIT_TPU_C_H_
#define RABIT_TPU_C_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* op enums: max=0 min=1 sum=2 bitor=3 (engine.h:195-200)
 * dtype enums: int8..float64 = 0..7 (rabit.py:209-218) */

/* argv-style "key=value" config strings */
int RbtInit(int argc, const char** argv);
int RbtFinalize(void);
int RbtGetRank(void);
int RbtGetWorldSize(void);
int RbtIsDistributed(void);
int RbtTrackerPrint(const char* msg);
/* writes up to *len bytes into buf; sets *len to the full length */
int RbtGetProcessorName(char* buf, size_t* len, size_t max_len);

int RbtAllreduce(void* sendrecvbuf, size_t count, int dtype, int op,
                 void (*prepare_fun)(void*), void* prepare_arg);
/* same, with a replay cache key (bootstrap cache, rabit.h:26-39) */
int RbtAllreduceEx(void* sendrecvbuf, size_t count, int dtype, int op,
                   void (*prepare_fun)(void*), void* prepare_arg,
                   const char* cache_key);
/* custom elementwise reducer over opaque fixed-size elements, for the
 * C++ Reducer/SerializeReducer templates (reference rabit.h:326-430;
 * engine.h:248-293 ReduceHandle). dst[i] = red(dst[i], src[i], ctx).
 * Like the whole API, not thread-safe: one custom reduction at a time. */
typedef void (*RbtReduceFn)(void* dst, const void* src, size_t count,
                            void* ctx);
int RbtAllreduceRaw(void* sendrecvbuf, size_t elem_size, size_t count,
                    RbtReduceFn red, void* red_ctx,
                    void (*prepare_fun)(void*), void* prepare_arg,
                    const char* cache_key);

/* Accelerator data-plane hook: payload allreduces with coded (dtype, op)
 * and nbytes >= min_bytes execute through fn (the XLA device-mesh
 * collective) instead of the socket tree/ring; sockets remain the
 * control plane (consensus, replay, checkpoints) and the small-message
 * path. ``epoch`` is the tracker link-registration epoch: when it
 * advances, the callback must tear down and re-form its fixed-membership
 * device world before reducing (get the coordinator via RbtCoordAddr).
 * fn returns 0 on success; nonzero enters the robust recovery path. */
typedef int (*RbtDataPlaneFn)(void* buf, uint64_t count, int dtype, int op,
                              uint32_t epoch, void* ctx);
int RbtSetDataPlane(RbtDataPlaneFn fn, void* ctx, uint64_t min_bytes);
/* current tracker link-registration epoch (advances on every recovery) */
int RbtWorldEpoch(void);
/* "host:port" of the current epoch's device-world coordinator (rank 0);
 * same buf/len convention as RbtGetProcessorName */
int RbtCoordAddr(char* buf, size_t* len, size_t max_len);

/* No-op whose address forces the linker to keep this library when a
 * binding is loaded only through static initializers (reference
 * RabitLinkTag, c_api.h:156-164):
 *   static int must_link_rabit_ = RbtLinkTag();  */
int RbtLinkTag(void);

int RbtBroadcast(void* sendrecvbuf, uint64_t size, int root);
/* same, with a replay cache key (bootstrap cache) */
int RbtBroadcastEx(void* sendrecvbuf, uint64_t size, int root,
                   const char* cache_key);

/* returns version number (0 = nothing checkpointed); out pointers are
 * owned by the library and valid until the next checkpoint call
 * (reference c_api.cc:219-245 static-buffer contract) */
int RbtLoadCheckpoint(const char** out_global, uint64_t* out_global_len,
                      const char** out_local, uint64_t* out_local_len);
int RbtCheckpoint(const char* global, uint64_t global_len,
                  const char* local, uint64_t local_len);
int RbtLazyCheckpoint(const char* global, uint64_t global_len);
int RbtVersionNumber(void);

/* In-process reset after the caller caught an exception mid-collective
 * (reference IEngine::InitAfterException, allreduce_robust.h:163-169);
 * robust engine only. */
int RbtInitAfterException(void);

/* In-process world resize (elastic membership): re-register with the
 * tracker and rebuild ring/tree links from the fresh assignment
 * without process exit. cmd is "recover" (survivor re-forming after an
 * eviction) or "join" (an evicted rank rejoining at the next epoch
 * boundary); NULL/"" defaults to "recover". Rank and world size may
 * both change; the robust engine's world-sized recovery state is reset
 * while checkpoints and the version counter survive. */
int RbtResize(const char* cmd);

/* Out-of-band interrupt (self-healing ladder, reform rung): ask the
 * collective currently blocked in the engine to bail out into the
 * robust layer's global re-formation instead of spinning on a wedged
 * link. Safe to call from any thread (the watchdog monitor); a no-op
 * when nothing consumes it. */
int RbtInterrupt(void);

/* RbtInterrupt with a provenance tag: ``reason`` (e.g. the watchdog
 * escalation rung that fired) is recorded alongside the flag and shows
 * up in recovery logs and RbtInterruptReason. NULL means "interrupt".
 * Same any-thread safety contract as RbtInterrupt. */
int RbtInterruptEx(const char* reason);

/* Most recent interrupt reason ("" if never raised). Sticky — reading
 * does not clear it, so post-recovery telemetry can attribute the last
 * reset. The returned pointer is owned by the library and stays valid
 * on the calling thread until its next RbtInterruptReason call. */
const char* RbtInterruptReason(void);

/* Recovery provenance counters (monotonic since Init): in-collective
 * round retries, CRC-rejected frames, and in-place link resurrections.
 * NULL out-pointers are skipped. */
int RbtRecoveryStats(uint64_t* retries, uint64_t* frame_rejects,
                     uint64_t* resurrects);

/* CRC-32 (IEEE/zlib polynomial) of buf — the checksum used by the
 * framed data plane (rabit_frame_crc); exposed so bindings/tests can
 * cross-check frames against zlib.crc32 without a second impl. */
uint32_t RbtFrameCrc32(const void* buf, uint64_t len);

/* last error message for bindings (empty string if none) */
const char* RbtGetLastError(void);

#ifdef __cplusplus
}
#endif

#endif /* RABIT_TPU_C_H_ */
