// rabit_tpu public C++ API — header-only templates over the C ABI.
//
// Capability parity with the reference's user-facing C++ surface
// (include/rabit/rabit.h + internal/rabit-inl.h): lifecycle
// (rabit.h:94-99), topology queries (rabit.h:102-112), TrackerPrint
// (rabit.h:119-130), three Broadcast overloads (rabit.h:142-175),
// Allreduce<OP,DType> with lazy prepare (rabit.h:200-242, fn-ptr and
// C++11 lambda variants), checkpointing (rabit.h:267-312), and the
// customized-reduction classes Reducer<DType,freduce> (rabit.h:326-368)
// and SerializeReducer<DType> (rabit.h:379-430).
//
// Fresh design: everything delegates through the flat C ABI
// (rabit_tpu_c.h) instead of an engine singleton header, so the public
// surface is one header + one shared library, and bindings in any
// language see exactly the same engine state. Caller-site replay keys
// (reference rabit.h:26-39 __builtin_FILE/LINE capture) are built the
// same way but flow through the ABI's explicit cache-key argument.
//
// Like the reference (rabit.h:177-178), this API is NOT thread-safe.
#ifndef RABIT_TPU_RABIT_H_
#define RABIT_TPU_RABIT_H_

#include <cstdarg>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <stdexcept>
#include <string>
#include <unordered_map>
#include <vector>

#include "../rabit_tpu_c.h"

namespace rabit {

// ---------------------------------------------------------------------------
// serialization substrate (reference serializable.h + internal/io.h —
// written fresh since dmlc-core is not a dependency here)
// ---------------------------------------------------------------------------

/// Abstract byte stream (reference dmlc::Stream re-export,
/// serializable.h:17-20).
class Stream {
 public:
  virtual ~Stream() = default;
  virtual size_t Read(void* ptr, size_t size) = 0;
  virtual void Write(const void* ptr, size_t size) = 0;
};

/// Growable in-memory stream (reference MemoryBufferStream, io.h:60-103).
class MemoryBufferStream : public Stream {
 public:
  explicit MemoryBufferStream(std::string* buf) : buf_(buf) {}
  size_t Read(void* ptr, size_t size) override {
    size_t n = buf_->size() - pos_;
    if (size < n) n = size;
    std::memcpy(ptr, buf_->data() + pos_, n);
    pos_ += n;
    return n;
  }
  void Write(const void* ptr, size_t size) override {
    if (pos_ + size > buf_->size()) buf_->resize(pos_ + size);
    std::memcpy(&(*buf_)[pos_], ptr, size);
    pos_ += size;
  }
  void Seek(size_t pos) { pos_ = pos; }
  size_t Tell() const { return pos_; }

 private:
  std::string* buf_;
  size_t pos_ = 0;
};

/// Fixed-region stream (reference MemoryFixSizeBuffer, io.h:22-58).
class MemoryFixSizeBuffer : public Stream {
 public:
  MemoryFixSizeBuffer(void* mem, size_t size)
      : mem_(static_cast<char*>(mem)), size_(size) {}
  size_t Read(void* ptr, size_t size) override {
    size_t n = size_ - pos_;
    if (size < n) n = size;
    std::memcpy(ptr, mem_ + pos_, n);
    pos_ += n;
    return n;
  }
  void Write(const void* ptr, size_t size) override {
    if (size == 0) return;
    if (pos_ + size > size_) {
      // silent truncation would corrupt SerializeReducer slots and
      // surface as wrong cluster-wide results with rc 0
      throw std::runtime_error(
          "MemoryFixSizeBuffer overflow: writing " + std::to_string(size) +
          " bytes at offset " + std::to_string(pos_) + " into a " +
          std::to_string(size_) + "-byte region (max_nbyte too small?)");
    }
    std::memcpy(mem_ + pos_, ptr, size);
    pos_ += size;
  }
  void Seek(size_t pos) { pos_ = pos; }
  size_t Tell() const { return pos_; }

 private:
  char* mem_;
  size_t size_;
  size_t pos_ = 0;
};

/// User-model serialization contract (reference dmlc::Serializable,
/// serializable.h:22-28): checkpointable state implements Load/Save.
class Serializable {
 public:
  virtual ~Serializable() = default;
  virtual void Load(Stream* fi) = 0;
  virtual void Save(Stream* fo) const = 0;
};

// ---------------------------------------------------------------------------
// reduction operators (reference op::Max/Min/Sum/BitOR,
// rabit-inl.h:66-102) and dtype mapping (rabit-inl.h:21-62)
// ---------------------------------------------------------------------------

namespace op {
struct Max {
  static const int kOp = 0;
  template <typename T>
  static void Reduce(T& dst, const T& src) { if (dst < src) dst = src; }
};
struct Min {
  static const int kOp = 1;
  template <typename T>
  static void Reduce(T& dst, const T& src) { if (src < dst) dst = src; }
};
struct Sum {
  static const int kOp = 2;
  template <typename T>
  static void Reduce(T& dst, const T& src) { dst += src; }
};
struct BitOR {
  static const int kOp = 3;
  template <typename T>
  static void Reduce(T& dst, const T& src) { dst |= src; }
};
}  // namespace op

namespace detail {

// C++ type -> wire dtype enum (matches rabit.py:209-218 and reducer.h);
// unmapped types get kRaw and reduce via the custom-reducer path.
template <typename T> struct DTypeEnum { static const int value = -1; };
template <> struct DTypeEnum<int8_t> { static const int value = 0; };
template <> struct DTypeEnum<uint8_t> { static const int value = 1; };
template <> struct DTypeEnum<int32_t> { static const int value = 2; };
template <> struct DTypeEnum<uint32_t> { static const int value = 3; };
template <> struct DTypeEnum<int64_t> { static const int value = 4; };
template <> struct DTypeEnum<uint64_t> { static const int value = 5; };
template <> struct DTypeEnum<float> { static const int value = 6; };
template <> struct DTypeEnum<double> { static const int value = 7; };

inline void Check(int rc, const char* what) {
  if (rc != 0) {
    std::string msg = std::string(what) + ": " + RbtGetLastError();
    throw std::runtime_error(msg);
  }
}

// Replay keys only matter for the pre-LoadCheckPoint bootstrap cache;
// once the first load happened, skip the string/map work on the hot path
// (the engine discards post-load keys anyway).
inline bool& LoadedFlag() {
  static bool loaded = false;
  return loaded;
}

// caller-signature replay key (reference rabit.h:26-39 semantics:
// file::line + payload, made unique per occurrence so repeated same-site
// calls stay distinguishable and stable across process restarts)
inline std::string CallKey(const char* file, int line, size_t nbytes,
                           size_t count) {
  if (LoadedFlag()) return std::string();
  static std::unordered_map<std::string, int> counts;
  std::string base = std::string(file) + "::" + std::to_string(line) + "#" +
                     std::to_string(nbytes) + "x" + std::to_string(count);
  int n = counts[base]++;
  return base + "@" + std::to_string(n);
}

// elementwise trampoline binding an OP functor over T to the ABI's raw
// custom-reducer signature
template <typename OP, typename T>
void OpReduce(void* dst, const void* src, size_t n, void*) {
  T* d = static_cast<T*>(dst);
  const T* s = static_cast<const T*>(src);
  for (size_t i = 0; i < n; ++i) OP::Reduce(d[i], s[i]);
}

}  // namespace detail

// ---------------------------------------------------------------------------
// lifecycle + topology (reference rabit.h:94-130)
// ---------------------------------------------------------------------------

/// Initialize the engine from argv-style "key=value" strings.
inline bool Init(int argc, char* argv[]) {
  std::vector<const char*> args(argv, argv + argc);
  return RbtInit(argc, args.data()) == 0;
}

/// Shut the engine down (must be the program's last rabit call).
inline bool Finalize() { return RbtFinalize() == 0; }

/// Reset engine state after catching an exception mid-collective so the
/// next collective starts clean (reference IEngine::InitAfterException,
/// allreduce_robust.h:163-169). Returns false (with RbtGetLastError set)
/// on the non-robust engines.
inline bool InitAfterException() { return RbtInitAfterException() == 0; }

inline int GetRank() { return RbtGetRank(); }
inline int GetWorldSize() { return RbtGetWorldSize(); }
inline bool IsDistributed() { return RbtIsDistributed() != 0; }

inline std::string GetProcessorName() {
  char buf[256];
  size_t len = 0;
  detail::Check(RbtGetProcessorName(buf, &len, sizeof(buf)),
                "GetProcessorName");
  if (len > sizeof(buf)) len = sizeof(buf);
  return std::string(buf, len);
}

/// Print a message from this worker through the tracker (rank 0 of the
/// tracker console; reference rabit.h:119-130).
inline void TrackerPrint(const std::string& msg) {
  detail::Check(RbtTrackerPrint(msg.c_str()), "TrackerPrint");
}

/// printf-style TrackerPrint (reference rabit.h:129,
/// rabit-inl.h:202-210).
#if defined(__GNUC__) || defined(__clang__)
__attribute__((format(printf, 1, 2)))
#endif
inline void TrackerPrintf(const char* fmt, ...) {
  const int kPrintBuffer = 1 << 10;
  std::string msg(kPrintBuffer, '\0');
  va_list args;
  va_start(args, fmt);
  std::vsnprintf(&msg[0], kPrintBuffer, fmt, args);
  va_end(args);
  msg.resize(std::strlen(msg.c_str()));
  TrackerPrint(msg);
}

#if defined(__GNUC__) || defined(__clang__)
#define RABIT_TPU_FILE __builtin_FILE()
#define RABIT_TPU_LINE __builtin_LINE()
#else
#define RABIT_TPU_FILE ""
#define RABIT_TPU_LINE 0
#endif

// ---------------------------------------------------------------------------
// collectives (reference rabit.h:142-242)
// ---------------------------------------------------------------------------

/// In-place broadcast of a raw buffer from rank root.
inline void Broadcast(void* sendrecv_data, size_t size, int root,
                      const char* file_ = RABIT_TPU_FILE,
                      int line_ = RABIT_TPU_LINE) {
  detail::Check(
      RbtBroadcastEx(sendrecv_data, size, root,
                     detail::CallKey(file_, line_, size, 1).c_str()),
      "Broadcast");
}

/// Broadcast a vector; non-root vectors are resized to match
/// (reference rabit.h:152-163 two-phase size-then-payload).
template <typename DType>
inline void Broadcast(std::vector<DType>* sendrecv_data, int root,
                      const char* file_ = RABIT_TPU_FILE,
                      int line_ = RABIT_TPU_LINE) {
  uint64_t size = sendrecv_data->size();
  detail::Check(
      RbtBroadcastEx(&size, sizeof(size), root,
                     detail::CallKey(file_, line_, sizeof(size), 1).c_str()),
      "Broadcast(size)");
  sendrecv_data->resize(size);
  if (size != 0) {
    Broadcast(sendrecv_data->data(), size * sizeof(DType), root, file_,
              line_);
  }
}

/// Broadcast a string (reference rabit.h:164-175).
inline void Broadcast(std::string* sendrecv_data, int root,
                      const char* file_ = RABIT_TPU_FILE,
                      int line_ = RABIT_TPU_LINE) {
  uint64_t size = sendrecv_data->size();
  detail::Check(
      RbtBroadcastEx(&size, sizeof(size), root,
                     detail::CallKey(file_, line_, sizeof(size), 1).c_str()),
      "Broadcast(size)");
  sendrecv_data->resize(size);
  if (size != 0) Broadcast(&(*sendrecv_data)[0], size, root, file_, line_);
}

/// In-place elementwise allreduce: sendrecvbuf[i] = OP over all ranks.
/// prepare_fun runs lazily right before the reduction executes and is
/// skipped when the engine replays a cached result during recovery
/// (reference rabit.h:200-221).
template <typename OP, typename DType>
inline void Allreduce(DType* sendrecvbuf, size_t count,
                      void (*prepare_fun)(void*) = nullptr,
                      void* prepare_arg = nullptr,
                      const char* file_ = RABIT_TPU_FILE,
                      int line_ = RABIT_TPU_LINE) {
  std::string key =
      detail::CallKey(file_, line_, sizeof(DType) * count, count);
  const int dtype = detail::DTypeEnum<DType>::value;
  if (dtype >= 0) {
    detail::Check(RbtAllreduceEx(sendrecvbuf, count, dtype, OP::kOp,
                                 prepare_fun, prepare_arg, key.c_str()),
                  "Allreduce");
  } else {
    detail::Check(
        RbtAllreduceRaw(sendrecvbuf, sizeof(DType), count,
                        detail::OpReduce<OP, DType>, nullptr, prepare_fun,
                        prepare_arg, key.c_str()),
        "Allreduce");
  }
}

namespace detail {
template <typename F>
void LambdaTrampoline(void* arg) { (*static_cast<F*>(arg))(); }
}  // namespace detail

/// Lambda-prepare variant (reference rabit.h:223-242).
template <typename OP, typename DType, typename F>
inline void Allreduce(DType* sendrecvbuf, size_t count, F prepare_fun,
                      const char* file_ = RABIT_TPU_FILE,
                      int line_ = RABIT_TPU_LINE) {
  Allreduce<OP, DType>(sendrecvbuf, count, detail::LambdaTrampoline<F>,
                       &prepare_fun, file_, line_);
}

// ---------------------------------------------------------------------------
// checkpointing (reference rabit.h:267-312)
// ---------------------------------------------------------------------------

/// Load the latest checkpoint; returns the version number (0 = nothing
/// stored, caller must initialize its model). local_model may be null
/// when no per-rank state is used.
inline int LoadCheckPoint(Serializable* global_model,
                          Serializable* local_model = nullptr) {
  const char *g = nullptr, *l = nullptr;
  uint64_t gn = 0, ln = 0;
  int version = RbtLoadCheckpoint(
      &g, &gn, local_model ? &l : nullptr, local_model ? &ln : nullptr);
  if (version < 0) detail::Check(-1, "LoadCheckPoint");
  detail::LoadedFlag() = true;
  if (version > 0) {
    if (global_model != nullptr && gn != 0) {
      std::string buf(g, gn);
      MemoryBufferStream fs(&buf);
      global_model->Load(&fs);
    }
    if (local_model != nullptr && ln != 0) {
      std::string buf(l, ln);
      MemoryBufferStream fs(&buf);
      local_model->Load(&fs);
    }
  }
  return version;
}

/// Checkpoint the model(s); bumps VersionNumber by one. global_model
/// must be identical on all ranks; local_model is per-rank state the
/// robust engine ring-replicates (reference rabit.h:288-300).
inline void CheckPoint(const Serializable* global_model,
                       const Serializable* local_model = nullptr) {
  std::string gbuf, lbuf;
  if (global_model != nullptr) {
    MemoryBufferStream fs(&gbuf);
    global_model->Save(&fs);
  }
  if (local_model != nullptr) {
    MemoryBufferStream fs(&lbuf);
    local_model->Save(&fs);
  }
  detail::Check(RbtCheckpoint(gbuf.data(), gbuf.size(),
                              local_model ? lbuf.data() : nullptr,
                              lbuf.size()),
                "CheckPoint");
}

/// Lazy checkpoint: the model is only serialized if a failure actually
/// needs it (reference rabit.h:301-305). The serialized form is captured
/// here and handed to the engine; the engine defers replication.
inline void LazyCheckPoint(const Serializable* global_model) {
  std::string gbuf;
  if (global_model != nullptr) {
    MemoryBufferStream fs(&gbuf);
    global_model->Save(&fs);
  }
  detail::Check(RbtLazyCheckpoint(gbuf.data(), gbuf.size()),
                "LazyCheckPoint");
}

inline int VersionNumber() { return RbtVersionNumber(); }

// ---------------------------------------------------------------------------
// customized reductions (reference rabit.h:326-430)
// ---------------------------------------------------------------------------

/// Custom elementwise reducer over a POD type with a compile-time reduce
/// function (reference Reducer<DType,freduce>, rabit.h:326-368).
template <typename DType, void (*freduce)(DType& dst, const DType& src)>
class Reducer {
 public:
  void Allreduce(DType* sendrecvbuf, size_t count,
                 void (*prepare_fun)(void*) = nullptr,
                 void* prepare_arg = nullptr,
                 const char* file_ = RABIT_TPU_FILE,
                 int line_ = RABIT_TPU_LINE) {
    std::string key =
        detail::CallKey(file_, line_, sizeof(DType) * count, count);
    detail::Check(RbtAllreduceRaw(sendrecvbuf, sizeof(DType), count, &Run,
                                  nullptr, prepare_fun, prepare_arg,
                                  key.c_str()),
                  "Reducer::Allreduce");
  }
  template <typename F>
  void Allreduce(DType* sendrecvbuf, size_t count, F prepare_fun,
                 const char* file_ = RABIT_TPU_FILE,
                 int line_ = RABIT_TPU_LINE) {
    Allreduce(sendrecvbuf, count, detail::LambdaTrampoline<F>, &prepare_fun,
              file_, line_);
  }

 private:
  static void Run(void* dst, const void* src, size_t n, void*) {
    DType* d = static_cast<DType*>(dst);
    const DType* s = static_cast<const DType*>(src);
    for (size_t i = 0; i < n; ++i) freduce(d[i], s[i]);
  }
};

/// Reducer for non-POD types that serialize into fixed-size slots
/// (reference SerializeReducer<DType>, rabit.h:379-430): DType implements
/// Load/Save (Serializable) and Reduce(const DType& src, size_t max_nbyte).
template <typename DType>
class SerializeReducer {
 public:
  /// Allreduce count objects, each serialized into a max_nbyte slot of
  /// sendrecvobj's staging buffer.
  void Allreduce(DType* sendrecvobj, size_t max_nbyte, size_t count,
                 void (*prepare_fun)(void*) = nullptr,
                 void* prepare_arg = nullptr,
                 const char* file_ = RABIT_TPU_FILE,
                 int line_ = RABIT_TPU_LINE) {
    buffer_.resize(max_nbyte * count);
    // serialize each object into its slot
    for (size_t i = 0; i < count; ++i) {
      MemoryFixSizeBuffer fs(&buffer_[i * max_nbyte], max_nbyte);
      sendrecvobj[i].Save(&fs);
    }
    Ctx ctx{sendrecvobj, max_nbyte};
    std::string key = detail::CallKey(file_, line_, max_nbyte * count,
                                      count);
    // reduce serialized slots; lazy prepare re-serializes first
    PrepCtx pctx{this, sendrecvobj, max_nbyte, count, prepare_fun,
                 prepare_arg};
    detail::Check(
        RbtAllreduceRaw(&buffer_[0], max_nbyte, count, &Run, &ctx,
                        prepare_fun ? &PrepRun : nullptr,
                        prepare_fun ? static_cast<void*>(&pctx) : nullptr,
                        key.c_str()),
        "SerializeReducer::Allreduce");
    // deserialize results back into the objects
    for (size_t i = 0; i < count; ++i) {
      MemoryFixSizeBuffer fs(&buffer_[i * max_nbyte], max_nbyte);
      sendrecvobj[i].Load(&fs);
    }
  }
  template <typename F>
  void Allreduce(DType* sendrecvobj, size_t max_nbyte, size_t count,
                 F prepare_fun,
                 const char* file_ = RABIT_TPU_FILE,
                 int line_ = RABIT_TPU_LINE) {
    Allreduce(sendrecvobj, max_nbyte, count, detail::LambdaTrampoline<F>,
              &prepare_fun, file_, line_);
  }

 private:
  struct Ctx {
    DType* objs;
    size_t max_nbyte;
  };
  struct PrepCtx {
    SerializeReducer* self;
    DType* objs;
    size_t max_nbyte;
    size_t count;
    void (*fn)(void*);
    void* arg;
  };
  // dst/src are serialized slots: deserialize both, reduce, re-serialize
  static void Run(void* dst, const void* src, size_t n, void* vctx) {
    Ctx* ctx = static_cast<Ctx*>(vctx);
    char* d = static_cast<char*>(dst);
    const char* s = static_cast<const char*>(src);
    DType tdst, tsrc;
    for (size_t i = 0; i < n; ++i) {
      MemoryFixSizeBuffer fd(d + i * ctx->max_nbyte, ctx->max_nbyte);
      MemoryFixSizeBuffer fsrc(const_cast<char*>(s) + i * ctx->max_nbyte,
                               ctx->max_nbyte);
      tdst.Load(&fd);
      tsrc.Load(&fsrc);
      tdst.Reduce(tsrc, ctx->max_nbyte);
      MemoryFixSizeBuffer fo(d + i * ctx->max_nbyte, ctx->max_nbyte);
      tdst.Save(&fo);
    }
  }
  // lazy prepare: run the user hook on the objects, then refresh the
  // serialized staging slots it will be reduced from
  static void PrepRun(void* varg) {
    PrepCtx* p = static_cast<PrepCtx*>(varg);
    p->fn(p->arg);
    for (size_t i = 0; i < p->count; ++i) {
      MemoryFixSizeBuffer fs(&p->self->buffer_[i * p->max_nbyte],
                             p->max_nbyte);
      p->objs[i].Save(&fs);
    }
  }

  std::string buffer_;
};

}  // namespace rabit

#endif  // RABIT_TPU_RABIT_H_
