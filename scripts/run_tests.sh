#!/usr/bin/env bash
# CI test runner — the role of the reference's scripts/travis_script.sh
# + travis_runtest.sh: build everything, then run every test tier on
# every push. Tiers mirror SURVEY §4:
#   0. lint (ruff when installed, tools/lint.py fallback)
#   1. native unit/self tests (single process)
#   2. multi-process integration with fault injection (tracker respawn)
#   3. device-mesh + model tests on the virtual CPU mesh
# Usage: scripts/run_tests.sh [quick]   ("quick" skips the slow
# recovery/stress tiers; default runs everything)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== tier 0: static analysis (tools/analysis framework) =="
if command -v ruff >/dev/null 2>&1; then
  ruff check rabit_tpu tools tests examples bench.py setup.py
fi
# ruff can't know the repo-specific contracts — telemetry spans
# (T001-T003), recovery counters (R003/R004), knob/protocol doc drift
# (R005/R006), or the lock-discipline rules (C001-C003, incl. the
# whole-repo lock-order graph); the full framework run covers those
# either way. Exit semantics: nonzero on any error-tier finding not in
# tools/analysis/baseline.txt.
python tools/lint.py

echo "== tier 0b: telemetry smoke (record -> export -> trace_report) =="
JAX_PLATFORMS=cpu python tools/trace_report.py --smoke \
    --dir /tmp/rabit_telemetry_smoke

echo "== tier 0c: chaos smoke (proxy -> injected reset -> retry) =="
python -m rabit_tpu.chaos --smoke

echo "== tier 0d: live-plane smoke (endpoint -> scrape -> flight) =="
python -m rabit_tpu.telemetry --smoke

echo "== tier 0e: regression-sentinel smoke (ingest -> MAD gate) =="
python tools/bench_sentinel.py --smoke

echo "== tier 0f: hierarchical dispatch smoke (sweep incl. hier column) =="
# one tiny size through every method — including the two-level hier
# schedule under a forced 2-ranks-per-host grouping — and the emitted
# table must round-trip through the dispatch loader
JAX_PLATFORMS=cpu python tools/collective_sweep.py --smoke \
    --out /tmp/rabit_sweep_smoke.json

echo "== tier 0g: skew-adaptation smoke (digest -> dispatch -> re-root) =="
# a forced skew digest must flow digest -> monitor -> dispatch
# provenance -> adapted (re-rooted tree) schedule on a 2-rank mesh,
# with the reduction still numerically correct
JAX_PLATFORMS=cpu python -m rabit_tpu.telemetry.skew --smoke

echo "== tier 0h: elastic-membership smoke (evict -> shrink -> rejoin) =="
# a live elastic tracker must evict a dead rank on wire evidence,
# re-form the survivors at N-1, park a late joiner until the epoch
# boundary, and re-admit it back to N — pure control plane, no jax
python -m rabit_tpu.tracker.membership --smoke

echo "== tier 0i: tracker-WAL smoke (journal -> crash -> resume) =="
# WAL format round-trip (torn-tail truncation, corrupt-middle hard
# error), then a live tracker journals a formation, crashes without
# cleanup, and a resume=True successor on the same port re-adopts the
# world — plus the chaos tracker_kill hook path (part of tier 0c)
python -m rabit_tpu.tracker.wal --smoke

echo "== tier 0j: async-dispatch smoke (issue -> overlap -> await) =="
# device_allreduce_async round-trip on a 1-host virtual mesh: bit-parity
# with the sync schedule, double-wait idempotency, and a live watchdog
# deadline armed per in-flight op (and never tripped); plus the hier
# three-phase pipeline behind one awaitable
JAX_PLATFORMS=cpu python tools/overlap_bench.py --smoke

echo "== tier 0k: failover smoke (replicate -> crash -> promote) =="
# an in-process leader+standby pair: the standby subscribes over the
# repl wire command, one journaled transition streams across and is
# acked (lag 0), then the leader crashes and the standby promotes on
# its reserved port only after the journaled lease expired — never
# while the leader's lease was still live (split-brain gate)
python -m rabit_tpu.tracker.standby --smoke

echo "== tier 0l: multi-job smoke (submit -> two worlds -> admission) =="
# one tracker, two fault-isolated jobs: both worlds form with
# independent ranks and epochs, a third job past rabit_max_jobs
# queues FIFO, a fourth past the queue depth is shed with a backoff
# hint, and closing a live job admits the queued one
python -m rabit_tpu.tracker.jobs --smoke

echo "== tier 0m: wire-quantization smoke (encode -> decode -> elect) =="
# block-quantized codec round-trips inside the documented error
# envelopes at several block sizes, the wire-spec grammar is total
# (junk rejected), and the adaptive election elects on a measured-slow
# fabric and declines on a fast one — pure host-side, no device mesh
JAX_PLATFORMS=cpu python -m rabit_tpu.parallel.wire --smoke

echo "== tier 0n: SLO plane + mini-soak (burn math -> chaos -> gate) =="
# the SLO evaluator's own smoke (histogram quantiles, burn states,
# family registration), then a ~60 s mini-soak: one leader+standby
# tracker pair behind the chaos proxy, a rolling handful of real jobs
# through admission, every chaos scenario live (incl. a tracker_kill
# -> promotion), asserting a well-formed soak/v1 artifact with all
# four fleet SLOs evaluated and the gate computed
python -m rabit_tpu.telemetry.slo --smoke
python tools/soak.py --smoke --quiet > /tmp/rabit_soak_smoke.json

echo "== tier 0o: C10k control-plane smoke (loop -> sched -> bench) =="
# the selectors event loop echoes framed commands through the fixed
# service pool (readiness ownership, per-key FIFO, shed-at-the-door
# cap); the fleet scheduler's fair shares + contended sweep +
# priority preemption run against a live multi-job tracker; then a
# scaled-down tracker_bench ramp proves held idle connections never
# grow the resident thread count and emits a well-formed
# tracker_bench/v1 artifact
python -m rabit_tpu.tracker.evloop --smoke
python -m rabit_tpu.tracker.autoscaler --smoke
python tools/tracker_bench.py --smoke --quiet

echo "== tier 0p: incident-plane smoke (HLC -> event bus -> attribution) =="
# hybrid logical clocks merge monotonically across skewed nodes, the
# fleet event ring keeps exact drop counts, and the incident engine
# attributes a violating SLO verdict to the seeded chaos cause (and
# marks an empty-window trigger explicitly unattributed)
python -m rabit_tpu.telemetry.incident --smoke

echo "== build native =="
cmake -S native -B native/build -G Ninja >/dev/null
cmake --build native/build --parallel

echo "== tier 1: native unit tests =="
./native/build/rt_selftest
./native/build/api_test

echo "== tier 1b: native TSan build (RT_SANITIZE=thread) =="
# clang also turns the rt_thread_annotations.h capability annotations
# into -Werror lock-discipline checks; under gcc they are no-ops and
# the dynamic race check has no toolchain, so skip with notice.
if command -v clang++ >/dev/null 2>&1; then
  cmake -S native -B native/build-tsan -G Ninja \
      -DCMAKE_C_COMPILER=clang -DCMAKE_CXX_COMPILER=clang++ \
      -DRT_SANITIZE=thread >/dev/null
  cmake --build native/build-tsan --parallel
  ./native/build-tsan/rt_selftest
  ./native/build-tsan/api_test
else
  echo "SKIPPED: clang/TSan not installed (gcc compiles the"
  echo "  thread-safety annotations as no-ops; install clang to enable"
  echo "  -Wthread-safety and -fsanitize=thread)"
fi

if [[ "${1:-}" == "quick" ]]; then
  echo "== quick: package + collectives + models =="
  python -m pytest tests/test_config.py tests/test_reducers.py \
      tests/test_api_single.py tests/test_collectives.py -q -x
  exit 0
fi

echo "== tier 2+3: full pytest suite =="
python -m pytest tests/ -q -x

echo "ALL TESTS PASSED"
